"""DenseRDD: the device tier — RDDs whose partitions are columnar array
shards on a jax Mesh and whose operations compile to SPMD XLA programs.

Architecture (SURVEY.md §7): partition == mesh shard; narrow op chains fuse
into ONE jitted shard_map program per stage (replacing the reference's Rust
iterator chaining, mapper_rdd.rs:161-163); a shuffle is ONE fused program of
  local pre-combine -> hash bucket -> all_to_all over ICI -> segment reduce
replacing the reference's entire shuffle machinery (dependency.rs:164-229,
shuffle_manager.rs, shuffle_fetcher.rs, map_output_tracker.rs) for on-mesh
data. "Within one TPU slice, a stage is a single SPMD program launch" — so
the per-task DAG fan-out collapses: the host DAGScheduler still owns the
graph, but a dense stage executes as one program, not num_partitions tasks.

DenseRDD subclasses RDD, so anything not device-accelerated (arbitrary
Python closures, cogroup with a host RDD, ...) transparently falls back to
the host tier through compute()/iterator() interop.

Raggedness: every block has static per-shard capacity; validity is
(count, mask). Exchange capacities are estimated, checked on device, and
retried with exact histogram-based sizes on overflow.

Related public work: DrJAX (arXiv:2403.07128) expresses MapReduce primitives
as JAX transforms the same way the dense tier lowers RDD ops to shard_map
programs; Exoshuffle (arXiv:2203.05072) argues for application-level,
pluggable shuffles — here the exchange implementation is planned per
launch (all_to_all | staged | ring, cost-modeled under the HBM budget by
tpu/exchange_plan.py, or forced via dense_exchange).
"""

from __future__ import annotations

import logging
import math
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from vega_tpu.errors import VegaError
from vega_tpu.rdd.base import RDD
from vega_tpu.split import Split
from vega_tpu.tpu import block as block_lib
from vega_tpu.tpu import dict_encoding
from vega_tpu.tpu import kernels
from vega_tpu.tpu import pallas_kernels
from vega_tpu.tpu import mesh as mesh_lib
from vega_tpu.tpu.block import KEY, KEY_LO, VALUE, Block

log = logging.getLogger("vega_tpu")

_SPEC = P(mesh_lib.SHARD_AXIS)
_REPL = P()


def _join_rename(nm: str, prefix: str) -> str:
    """VALUE -> lv/rv and VALUE.lo -> lv.lo/rv.lo by EXACT match — a
    substring replace would mangle any future name containing 'v'. Only
    canonical layouts reach the join (see _dense_joinable), so anything
    else passing through unchanged is a programming error upstream."""
    if nm == VALUE:
        return prefix
    if nm == block_lib.lo_of(VALUE):
        return block_lib.lo_of(prefix)
    return nm


def _canonical_value_layout(schema) -> bool:
    """True when the non-key columns are exactly the canonical VALUE — or
    the wide (VALUE, VALUE.lo) int64 pair — i.e. the block has a host-tier
    (k, v) row form and the lv/rv join renames apply cleanly."""
    names = [nm for nm, _ in schema if nm not in (KEY, KEY_LO)]
    return names in ([VALUE], [VALUE, block_lib.lo_of(VALUE)])


def _shard_program(mesh, fn, in_specs, out_specs):
    """jit(shard_map(fn))."""
    from vega_tpu.tpu import compat

    if isinstance(in_specs, int):
        in_specs = (_SPEC,) * in_specs
    return jax.jit(
        compat.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        )
    )


# Structural program cache: identical pipelines (same op kinds, same closure
# code, same static capacities) reuse one compiled XLA program across RDD
# instances — the replacement for the reference's "serialize the closure"
# portability story (SURVEY.md §2.1): here the *fingerprint* of the traced
# function is the identity, and XLA's own jit cache handles shape changes.
_PROGRAM_CACHE: dict = {}
# Programs minted (built, not served from the cache) since process start.
# The frame planner's whole-stage-fusion acceptance test reads this to
# prove a select->filter->with_column chain compiled to ONE program.
_PROGRAM_MINTS: int = 0


def program_mints() -> int:
    """Count of shard programs BUILT so far (cache hits excluded)."""
    return _PROGRAM_MINTS


def _fp(obj) -> str:
    """Stable fingerprint of a callable/closure for program-cache keys."""
    import hashlib

    try:
        import cloudpickle

        return hashlib.sha1(cloudpickle.dumps(obj)).hexdigest()[:16]
    except Exception:  # noqa: BLE001 — unpicklable: identity-cached only
        return f"id:{id(obj)}"


def _cached_program(key, build):
    global _PROGRAM_MINTS
    prog = _PROGRAM_CACHE.get(key)
    if prog is None:
        prog = build()
        _PROGRAM_CACHE[key] = prog
        _PROGRAM_MINTS += 1
    return prog


class _HostMeshStub:
    """Stands in for a jax Mesh on the far side of a pickle: Block only
    reads .size, and mesh_lib.host_get passes numpy through, so a Block whose
    columns are host numpy works unchanged for reading."""

    def __init__(self, size: int):
        self.size = size


# ---------------------------------------------------------------------------
# dense block lifetime (HBM accounting + LRU eviction)
# ---------------------------------------------------------------------------
#
# Every materialized intermediate registers in a per-Context LRU keyed by
# node identity. When the tracked resident bytes exceed
# Configuration.dense_hbm_budget, least-recently-used blocks are RELEASED:
# the node's memoized Block reference is dropped, so HBM frees once no
# computation holds the buffers, and the next access re-materializes from
# lineage — recompute-over-spill, the device analogue of the host tier's
# BoundedMemoryCache LRU (cache.py; the reference leaves eviction as
# todo!(), cache.rs:68-76). Sources are exempt (their Block IS the data —
# nothing to rebuild from; their footprint is gated at creation by the
# streaming planner) and so are unsettled speculative blocks (their pending
# entry must settle/repair through the SAME object).
#
# Multi-process note: in SPMD multihost runs the driver program is
# replicated, so eviction decisions must be identical on every process —
# a divergent decision would make one process re-dispatch exchange
# collectives the others skip. Round-4 advisor finding: weakref liveness
# (GC timing) and LRU touch order are NOT replicated — a user reference
# cycle collects at process-divergent times, and concurrent host-tier
# task threads reorder touches thread-interleaving-dependently. So when
# jax.process_count() > 1 the policy hardens to a deterministic FIFO:
#   - entries are keyed by rdd_id (allocation order is replicated;
#     id() reuse after GC is not),
#   - touches do not reorder (registration order is the eviction order),
#   - accounting uses the byte size RECORDED AT REGISTRATION and an
#     entry leaves the accounting only via eviction or explicit
#     release — never via weakref death (a dead entry's eviction is a
#     deterministic no-op pop; its HBM freed when the object died, only
#     the accounting persists until the sweep reaches it).
# Single-process keeps true LRU with live-byte accounting and dead-ref
# pruning (no cross-process divergence to protect against there).


_lifetime_multiproc_memo: Optional[bool] = None


def _lifetime_multiproc() -> bool:
    # Safe to ask jax here: lifetime hooks only run on nodes that hold a
    # materialized device block, so the backend is already initialized
    # (the CLAUDE.md "never probe backends" rule is about import paths
    # and pre-init probes on a wedged tunnel). Memoized — process count
    # is fixed once jax.distributed is up (Context joins the mesh before
    # any materialization), and this runs on every touch/sweep in the
    # hot block_spec() path.
    global _lifetime_multiproc_memo
    if _lifetime_multiproc_memo is None:
        try:
            _lifetime_multiproc_memo = jax.process_count() > 1
        except Exception:  # noqa: BLE001 — no backend: single-process
            return False  # don't memoize a pre-init answer
    return _lifetime_multiproc_memo


def _reset_lifetime_multiproc_memo() -> None:
    """mesh.init_multihost calls this next to set_default_mesh(None): a
    process that materialized dense blocks under a single-process Context
    and then joined a jax.distributed mesh (stop() + new multihost Context
    is supported) must re-resolve the eviction policy — keeping the stale
    False memo would run the LRU/weakref policy on a multi-process mesh,
    the exact cross-process divergence the FIFO hardening prevents."""
    global _lifetime_multiproc_memo
    _lifetime_multiproc_memo = None


def _lifetime_lru(ctx) -> dict:
    return ctx.__dict__.setdefault("_dense_block_lru", {})


def _lifetime_touch(rdd) -> None:
    lru = rdd.context.__dict__.get("_dense_block_lru")
    if lru is None:
        return
    if _lifetime_multiproc():
        return  # FIFO: touch order is thread-interleaving-dependent
    entry = lru.pop(rdd.rdd_id, None)
    if entry is not None:
        lru[rdd.rdd_id] = entry  # re-insert at MRU end


def _lifetime_register(rdd) -> None:
    lru = _lifetime_lru(rdd.context)
    blk = rdd._block
    lru.pop(rdd.rdd_id, None)
    lru[rdd.rdd_id] = (weakref.ref(rdd), blk.nbytes if blk is not None
                       else 0)
    _lifetime_evict(rdd.context, keep=rdd.rdd_id)


def _lifetime_forget(rdd) -> None:
    lru = rdd.context.__dict__.get("_dense_block_lru")
    if lru is not None:
        lru.pop(rdd.rdd_id, None)


def _lifetime_sweep(lru: dict, multiproc: bool) -> Tuple[int, list]:
    """Return (total tracked bytes, candidate keys in eviction order).
    Single-process: prunes dead/evicted entries and counts live block
    bytes (LRU->MRU order). Multi-process: counts REGISTERED bytes of
    every entry, dead or alive, in registration order — liveness is GC
    timing, which diverges across processes, so it must not influence
    totals or ordering (dead entries fall out when the evictor reaches
    them, identically everywhere). Concurrent-safe against evict/
    unpersist on other host-tier task threads: every read is a single
    snapshot (.get, one _block capture), never a check-then-reread."""
    live = []
    total = 0
    for key in list(lru):
        entry = lru.get(key)
        if entry is None:
            continue
        ref, reg_bytes = entry
        if multiproc:
            total += reg_bytes
            live.append(key)
            continue
        rdd = ref()
        blk = rdd._block if rdd is not None else None
        if blk is None:
            lru.pop(key, None)
            continue
        total += blk.nbytes
        live.append(key)
    return total, live


def dense_hbm_in_use(ctx) -> int:
    """Tracked device-resident bytes of materialized dense intermediates
    (sources excluded — see the lifetime note above). Single-process this
    prunes dead refs and reports live bytes; multi-process it reports the
    deterministic registered-byte accounting (which may briefly include
    blocks whose owner died — see the multi-process note)."""
    lru = ctx.__dict__.get("_dense_block_lru")
    if not lru:
        return 0
    return _lifetime_sweep(lru, _lifetime_multiproc())[0]


def _lifetime_evict(ctx, keep: Optional[int] = None) -> None:
    from vega_tpu.env import Env

    budget = getattr(Env.get().conf, "dense_hbm_budget", 4 << 30)
    lru = ctx.__dict__.get("_dense_block_lru")
    if not lru:
        return
    multiproc = _lifetime_multiproc()
    total, live = _lifetime_sweep(lru, multiproc)
    if total <= budget:
        return
    for key in live:  # registration (FIFO) / LRU order
        if total <= budget:
            break
        if key == keep:
            continue
        entry = lru.get(key)
        if entry is None:
            continue
        ref, reg_bytes = entry
        rdd = ref()
        blk = rdd._block if rdd is not None else None
        if blk is None:
            # Dead or already-released: deterministic pop, accounting
            # freed. (Multi-process: one process's GC may see the object
            # alive while another's doesn't — both still pop this entry
            # here, subtract the same registered bytes, and dispatch no
            # collectives, so decisions stay aligned.)
            total -= reg_bytes if multiproc else 0
            lru.pop(key, None)
            continue
        if blk.settle is not None:
            # Pending speculation: evictable only once settled. This
            # check is multi-process deterministic: a pending node is
            # strongly held by ctx._dense_pending (entry["rdd"]) on
            # every process, so ref() cannot be dead on one process and
            # pending-alive on another.
            continue
        level = getattr(rdd, "_storage_level", None)
        if level is not None and level.use_disk:
            # persist(MEMORY_AND_DISK / DISK_ONLY): demote the block to
            # the disk tier instead of dropping it — the next access
            # promotes (reload + reshard) rather than recomputing
            # lineage. Accounting below is identical either way (the
            # entry leaves the LRU with the same registered bytes), so
            # the FIFO/registered-byte invariants are untouched.
            _demote_block_to_disk(rdd, blk)
        total -= reg_bytes if multiproc else blk.nbytes
        rdd._block = None
        rdd.__dict__.pop("_pickle_state_memo", None)
        lru.pop(key, None)
        log.debug("dense lifetime: evicted block of rdd %s (%d bytes)",
                  rdd.rdd_id, blk.nbytes)


def _dense_spill_key(rdd) -> str:
    return f"dense-{rdd.rdd_id}"


def _demote_block_to_disk(rdd, blk) -> bool:
    """Write an evicted node's block to the disk tier (store/ DiskStore,
    via the TieredCache raw-block API so spill bytes are counted and a
    BlockSpilled event reaches the bus) as a host-numpy snapshot in the
    SAME shard layout splits() uses for host interop: concatenated
    [n_shards * capacity] columns + per-shard counts + capacity. Promotion
    reproduces device placement bit-identically, so a reloaded node's
    hash_placed/key_sorted claims stay true.

    Multi-process meshes skip demotion (drop-and-recompute, as before):
    gathering host columns dispatches a collective, and eviction can run
    on host-tier task threads whose interleaving is not replicated across
    processes — the same reason splits() pre-gathers on the driver
    thread. A failed spill degrades to recompute, never to bad data."""
    import io

    from vega_tpu.env import Env

    first = next(iter(blk.cols.values()), None)
    if isinstance(first, jax.Array) and not first.is_fully_addressable:
        return False
    cache = Env.get().cache
    if not hasattr(cache, "spill_raw"):  # bare memory cache (unit tests)
        return False
    key = _dense_spill_key(rdd)
    if cache.contains_raw(key):
        return True  # blocks are immutable per rdd_id: one demotion is enough
    try:
        buf = io.BytesIO()
        arrays = {f"col:{n}": np.asarray(c)
                  for n, c in blk.host_cols().items()}
        np.savez(buf, counts=blk.counts_np,
                 capacity=np.int64(blk.capacity), **arrays)
        cache.spill_raw(key, buf.getvalue(), store="dense")
        return True
    except Exception:  # noqa: BLE001 — spill failure means recompute, not loss
        log.exception("dense block spill failed; node will recompute")
        return False


def _load_spilled_block(rdd) -> "Optional[Block]":
    """Promote a demoted node's block back onto the device mesh (checksummed
    read through the disk tier; a corrupt or mesh-mismatched snapshot is a
    miss and the node recomputes from lineage)."""
    import io

    from vega_tpu.env import Env

    level = getattr(rdd, "_storage_level", None)
    if level is None or not level.use_disk:
        return None
    cache = Env.get().cache
    if not hasattr(cache, "read_raw"):
        return None
    data = cache.read_raw(_dense_spill_key(rdd), store="dense")
    if data is None:
        return None
    try:
        with np.load(io.BytesIO(data)) as z:
            counts = np.asarray(z["counts"])
            capacity = int(z["capacity"])
            cols = {n[len("col:"):]: np.asarray(z[n])
                    for n in z.files if n.startswith("col:")}
    except Exception:  # noqa: BLE001
        log.warning("dense spill snapshot unreadable; recomputing",
                    exc_info=True)
        cache.remove_raw(_dense_spill_key(rdd))
        return None
    if len(counts) != rdd.mesh.size:
        return None  # mesh changed since the spill: recompute
    spec = mesh_lib.shard_spec(rdd.mesh)
    return Block(
        cols={n: mesh_lib.host_put(c, spec) for n, c in cols.items()},
        counts=mesh_lib.host_put(counts, spec),
        capacity=capacity, mesh=rdd.mesh, counts_host=counts,
    )


# Attributes a detached clone must NOT carry: lineage links, the Context,
# materialized blocks, and speculation state. Everything else (user fns,
# schemas, op names, scalars) is the per-shard transform state cached
# programs legitimately need for retraces.
_HEAVY_ATTRS = frozenset({
    "context", "_deps", "_dense_parents", "parent", "left", "right",
    "first", "second", "_block", "_pickle_state_memo", "_fp_memo",
    "_cfp_memo", "_checkpointed_rdd", "_deferred_entry",
    "_host_stage_block",
})


def _heavy_value(v) -> bool:
    """Fail-closed backstop for _detach: any attribute VALUE that is (or
    contains, at any container depth) an RDD or Block pins lineage/HBM if
    captured in a process-lifetime program closure — strip it even under
    a name _HEAVY_ATTRS doesn't know (e.g. a future `self.table =
    other_rdd`). Full recursion through tuples/lists/sets/dicts (an RDD
    inside a dict-valued attribute must not slip through); the visited
    set bounds cyclic structures."""
    stack = [v]
    seen = set()
    while stack:
        x = stack.pop()
        if isinstance(x, (RDD, Block)):
            return True
        if id(x) in seen:
            continue
        if isinstance(x, (tuple, list, set, frozenset)):
            seen.add(id(x))
            stack.extend(x)
        elif isinstance(x, dict):
            seen.add(id(x))
            stack.extend(x.keys())
            stack.extend(x.values())
    return False


def _detach(node):
    """Light clone of a node for program-cache closures.

    Programs in the structural cache live for the process (they retrace on
    new capacities), so a closure that captures the node itself pins its
    whole lineage — parents, Context, and every block those ever
    materialize, including un-evictable source data — long after the
    pipeline dies. The clone shares the node's class (so _shard_fn /
    _segment_reduce and friends work unchanged) but carries only the
    light transform state, never lineage or blocks: known-heavy names are
    denylisted, and _heavy_value strips RDD/Block-valued attributes under
    ANY name so a new attribute fails closed, not open."""
    clone = object.__new__(type(node))
    clone.__dict__.update(
        (k, v) for k, v in node.__dict__.items()
        if k not in _HEAVY_ATTRS and not _heavy_value(v))
    return clone


def _detached_chain(chain):
    return [_detach(nd) for nd in chain]


def _yield_rows(rows: dict):
    """Host-facing row iteration over one shard's columns — shared by
    DenseRDD.compute and the unpickled _HostDenseView so the two tiers'
    row semantics cannot drift."""
    names = list(rows)
    if names == [VALUE]:
        yield from rows[VALUE].tolist()
    elif set(names) == {KEY, VALUE}:
        yield from zip(rows[KEY].tolist(), rows[VALUE].tolist())
    else:
        cols = [rows[n] for n in names]
        for i in range(len(cols[0])):
            yield tuple(c[i] for c in cols)


class DenseRDD(RDD):
    """Base dense node. Subclasses implement _materialize() -> Block."""

    def __init__(self, ctx, mesh, deps_rdds: Sequence["DenseRDD"] = ()):
        from vega_tpu.dependency import OneToOneDependency

        super().__init__(ctx, deps=[OneToOneDependency(r) for r in deps_rdds])
        self.mesh = mesh
        self._dense_parents = tuple(deps_rdds)
        self._block: Optional[Block] = None

    def _fp_extra(self):
        """Node-type-specific part of the structural lineage fingerprint
        (closure fingerprints, op names, flags)."""
        return ()

    def _lineage_fp(self):
        """Structural identity of this node's lineage: node types + their
        parameters, NOT rdd ids — two runs of the same pipeline (fresh
        nodes, same shape) share a fingerprint. Keys the exchange capacity
        hints so warm re-runs skip the sizing histogram's device round
        trip (the overflow-retry loop remains the safety net if the data
        distribution changed). Iterative walk + per-node memo: lineages
        can be thousands of narrow nodes deep (the chain materializer
        supports that depth, so this must too), and _fp_extra pickles
        closures — compute each node's fingerprint once."""
        if getattr(self, "_fp_memo", None) is None:
            stack = [(self, False)]
            while stack:
                node, ready = stack.pop()
                if getattr(node, "_fp_memo", None) is not None:
                    continue
                if ready:
                    node._fp_memo = (
                        type(node).__name__, node._fp_extra(),
                    ) + tuple(p._fp_memo for p in node._dense_parents)
                else:
                    stack.append((node, True))
                    stack.extend((p, False) for p in node._dense_parents)
        return self._fp_memo

    # --- process portability ------------------------------------------------
    def __getstate__(self):
        """Dense nodes cross process boundaries as HOST data: jax arrays,
        meshes, and traced programs are process-local, so the block is
        materialized at pickle time (driver side) and ships as numpy
        columns. The restored object is a _HostDenseView — same shard
        structure, iteration-only (the moral analogue of the reference's
        ParallelCollectionSplit carrying its data slice inside the split,
        parallel_collection_rdd.rs:30-56).

        Memoized: a host-tier stage with P tasks pickles this node P times
        (one dumps per task, distributed/backend.py), so the device->host
        gather happens once, not per task. NOTE pickling is intended for
        driver-side task serialization; an incidental pickle (e.g. a user
        closure capturing a DenseRDD) also materializes the node here."""
        memo = getattr(self, "_pickle_state_memo", None)
        if memo is None:
            blk = self.block()
            memo = {
                "context": self.context,
                "rdd_id": self.rdd_id,
                "should_cache": self.should_cache,
                "_pinned": self._pinned,
                "cols": {n: np.asarray(c) for n, c in
                         mesh_lib.host_get(dict(blk.cols)).items()},
                "counts": blk.counts_np,
                "capacity": blk.capacity,
                "dicts": blk.dicts,
            }
            self._pickle_state_memo = memo
        return memo

    def __setstate__(self, state):
        self.__class__ = _HostDenseView
        self.context = state["context"]
        self.rdd_id = state["rdd_id"]
        self._deps = []
        self._partitioner = None
        self.should_cache = state["should_cache"]
        self._pinned = state["_pinned"]
        self._checkpoint_dir = None
        self._checkpointed_rdd = None
        self._host_block = Block(
            cols=state["cols"], counts=state["counts"],
            capacity=state["capacity"],
            mesh=_HostMeshStub(len(state["counts"])),
            dicts=state.get("dicts"),
        )

    def dense(self):
        """Already on the device tier — identity (RDD.dense() lifts host
        lineages; re-lifting a dense node would round-trip the data)."""
        return self

    # --- device plane -------------------------------------------------------
    def block(self) -> Block:
        """Materialize this node's Block (memoized — dense lineage is
        materialized-once, which is the finished version of the reference's
        half-built .cache(), SURVEY.md §2.6). SETTLED: any pending
        speculative exchange is verified (and repaired on overflow) before
        the block is handed out, so callers may trust its data. Launch
        sites that can tolerate speculation (exchange materializers, whose
        outputs register their own pending entry) use block_spec()."""
        blk = self.block_spec()
        if blk.settle is not None:
            blk.settle()
        return blk

    def block_spec(self) -> Block:
        """block() without settlement: the returned Block may still carry
        an unverified overflow flag. Only for consumers that register
        their own pending entry (so a failed speculation invalidates and
        repairs them too) — everything else must use block()."""
        blk = self._block
        if blk is None:
            # A demoted (persist-to-disk) block promotes from the spill
            # tier — a disk hit, not a lineage recompute; anything else
            # rematerializes from lineage.
            blk = _load_spilled_block(self)
            if blk is None:
                blk = self._materialize()
            if blk.dicts is None:
                # ONE attachment point for the dictionary sidecar: every
                # materializer builds plain code-column Blocks; the
                # lineage-propagated dictionaries (_dicts) hang on here so
                # host-facing reads (to_numpy/shard_rows) decode. Sources
                # already carry dicts from from_numpy and keep theirs.
                d = self._dicts()
                if d:
                    blk.dicts = dict(d)
            self._block = blk
            # Only lineage-recomputable nodes enter the eviction LRU:
            # sources set _block in __init__ and never take this path.
            # Return the captured local: a concurrent eviction (host-tier
            # task threads share dense nodes) may null _block again.
            _lifetime_register(self)
        else:
            _lifetime_touch(self)
        return blk

    def persist(self, level=None) -> "DenseRDD":
        """Storage level for this node's materialized device block. Dense
        nodes are materialized-once already (block() memoizes — the
        finished .cache()); MEMORY_AND_DISK / DISK_ONLY additionally make
        HBM-budget eviction *demote* the block to the disk tier as a
        host-numpy snapshot instead of dropping it, and the next access
        *promote* it (reload + reshard, placement-identical) instead of
        recomputing lineage. Device data must be HBM-resident to compute,
        so for dense nodes DISK_ONLY behaves like MEMORY_AND_DISK. Does
        NOT engage the host-tier row cache (should_cache): dense
        partitions live as blocks, not row lists."""
        from vega_tpu.store import StorageLevel

        self._storage_level = StorageLevel.coerce(level)
        return self

    def unpersist(self) -> "DenseRDD":
        """Release this node's materialized device block (the analogue of
        the host tier's uncache; reference eviction is todo!(),
        cache.rs:68-76). Pending speculation settles first so a captured
        Block reference can't observe truncated data. The next access
        re-materializes from lineage. Returns self for chaining."""
        blk = self._block
        if blk is not None:
            if blk.settle is not None:
                blk.settle()
            self._block = None
            self.__dict__.pop("_pickle_state_memo", None)
            _lifetime_forget(self)
        self.__dict__.pop("_host_stage_block", None)
        from vega_tpu.env import Env

        cache = Env.get().cache
        if hasattr(cache, "remove_raw"):  # drop any demoted disk snapshot
            cache.remove_raw(_dense_spill_key(self))
        return self

    def _counts_fp(self):
        """Fetch-free identity of this node's input sizes: materialized
        counts where already host-known, else the tuple of parent
        identities down to leaf sources (whose counts are always
        host-known). Keys the exchange capacity hints WITHOUT forcing the
        driver-blocking counts fetch that keyed them in round 2 — that
        fetch was the RTT between pipelined launches. Same lineage + same
        leaf counts but different data values can alias; the overflow
        retry (settle-repair) is the safety net, as ever."""
        memo = getattr(self, "_cfp_memo", None)
        if memo is not None:
            return memo
        if self._dense_parents:
            # Non-leaf nodes ALWAYS use the structural parents form —
            # never their own materialized counts, which would make the
            # fingerprint depend on whether the node happened to be
            # settled when first fingerprinted (identical warm reruns
            # would mint different hint keys and miss the cache).
            # Iterative (chains can be thousands of nodes deep).
            stack = [(self, False)]
            while stack:
                node, ready = stack.pop()
                if getattr(node, "_cfp_memo", None) is not None:
                    continue
                if not node._dense_parents:
                    node._cfp_memo = node.block_spec().counts_np.tobytes()
                elif ready:
                    node._cfp_memo = tuple(
                        p._cfp_memo for p in node._dense_parents)
                else:
                    stack.append((node, True))
                    stack.extend((p, False) for p in node._dense_parents)
        else:
            # Leaf source: counts are builder-known (block_range /
            # from_numpy / dense_from_block set counts_host) — at worst
            # a settle, never a separate fetch.
            self._cfp_memo = self.block_spec().counts_np.tobytes()
        return self._cfp_memo

    def _materialize(self) -> Block:
        raise NotImplementedError

    @property
    def is_pair(self) -> bool:
        return KEY in dict(self._schema())

    @property
    def hash_placed(self) -> bool:
        """True when every key's rows provably live only on shard
        hash(key) % n — the output of any hash exchange. Downstream
        keyed shuffles over hash-placed inputs elide the exchange
        entirely (one per-shard program, zero collectives): the device
        analogue of the host tier's partitioner-equality shuffle elision
        (reference: co_grouped_rdd.rs:102-127, a CLAUDE.md invariant).
        Key-preserving narrow ops propagate it; anything that can rewrite
        keys resets it.

        PURE: reading this property never materializes anything. Nodes
        whose placement is only knowable post-materialization (the
        reduce's host-exact fold takeover) answer conservatively (False)
        while unmaterialized; exchange planners call _settle_placement()
        first to get the materialized truth."""
        return False

    @property
    def key_sorted(self) -> bool:
        """True when each shard's valid rows are provably key-sorted
        (reduce/group/join outputs). Together with hash_placed this lets
        downstream keyed ops skip their own sort: order survives the
        stable compact of an elided (passthrough) exchange, but NOT a real
        exchange or a union concat."""
        return False

    def _settle_placement(self) -> None:
        """Make hash_placed/key_sorted answer truthfully, materializing
        whatever that requires (explicit side effect — the property reads
        themselves stay pure). Narrow nodes forward to the parent their
        placement delegates to; the reduce materializes itself (its
        host-fold takeover is only known post-exchange); everything else
        is a no-op. Exchange planners MUST call this on an input before
        reading its flags for an elision decision (round-4 advisor:
        a bare property read must never launch an exchange)."""

    def _schema(self) -> Tuple[Tuple[str, jnp.dtype], ...]:
        """(name, dtype) of columns without materializing."""
        raise NotImplementedError

    def _dicts(self) -> Dict[str, np.ndarray]:
        """{column name -> sorted host dictionary array} for every
        dictionary-encoded (string) column of THIS node's output
        (tpu/dict_encoding.py). Pure host metadata, known at
        graph-construction time — never materializes device data.

        Default: union of the parents' dictionaries (first parent wins a
        name tie — binary nodes that mix sides override), filtered to
        this node's schema. Nodes that mint or move columns set
        `_dict_renames` ({out name -> parent name}), which REPLACES the
        walk: only listed columns inherit dict-ness ({} = mints all
        columns fresh, e.g. a traced map). Memoized per node (lineage
        walks are repeated by every public-method gate)."""
        memo = getattr(self, "_dicts_memo", None)
        if memo is not None:
            return memo
        parent_dicts: Dict[str, np.ndarray] = {}
        for p in self._dense_parents:
            for nm, d in p._dicts().items():
                parent_dicts.setdefault(nm, d)
        renames = getattr(self, "_dict_renames", None)
        if renames is not None:
            out = {out_nm: parent_dicts[src]
                   for out_nm, src in renames.items() if src in parent_dicts}
        else:
            out = parent_dicts
        names = {nm for nm, _ in self._schema()}
        res = {nm: d for nm, d in out.items() if nm in names}
        self._dicts_memo = res
        return res

    # --- RDD interop (host tier sees a normal RDD) --------------------------
    @property
    def num_partitions(self) -> int:
        return self.mesh.size

    def _spans_processes(self) -> bool:
        """Does this node's data live on a multi-process (jax.distributed)
        mesh? Read from the materialized block when there is one (no
        backend probe); otherwise from the mesh's device->process map —
        safe, because a Mesh only exists after backend init."""
        blk = self._block
        if blk is not None and blk.cols:
            first = next(iter(blk.cols.values()))
            return (isinstance(first, jax.Array)
                    and not first.is_fully_addressable)
        devs = getattr(self.mesh, "devices", None)
        if devs is None:  # _HostMeshStub: host data, single process
            return False
        try:
            return len({d.process_index for d in devs.flat}) > 1
        except Exception:  # noqa: BLE001 — stub/CPU meshes: no span
            return False

    def splits(self) -> List[Split]:
        # Host-tier interop only (dense actions bypass the scheduler).
        # On a multi-process mesh the block is materialized AND
        # snapshotted to host numpy HERE: splits() runs on the driver
        # thread at stage submission (dag.py submit_missing_tasks /
        # _get_preferred_locs), while compute() fans out to scheduler
        # task threads whose interleaving differs across processes — and
        # jax.distributed collectives must be dispatched in the same
        # order on every process. Materializing here (not just
        # pre-gathering an already-built block, as rounds 3-4 did) also
        # closes the round-4 advisor race: _lifetime_evict may null
        # _block between stage submission and compute(), and the
        # re-materialization would otherwise dispatch collectives from
        # task threads. The snapshot hangs off the node (not the LRU'd
        # Block), so a mid-stage eviction cannot resurrect device work;
        # unpersist() drops it.
        if self._spans_processes() \
                and self.__dict__.get("_host_stage_block") is None:
            blk = self.block()  # driver thread: deterministic collectives
            self._host_stage_block = Block(
                cols={n: np.asarray(c)
                      for n, c in blk.host_cols().items()},
                counts=blk.counts_np, capacity=blk.capacity,
                mesh=_HostMeshStub(self.mesh.size),
                dicts=blk.dicts,
            )
        return [Split(i) for i in range(self.num_partitions)]

    def compute(self, split: Split, task_context=None):
        hb = self.__dict__.get("_host_stage_block")
        if hb is not None:  # multi-process: device-free task threads
            yield from _yield_rows(hb.shard_rows(split.index))
            return
        yield from _yield_rows(self.block().shard_rows(split.index))

    @property
    def columns(self) -> List[str]:
        return [n for n, _ in self._schema()]

    def select(self, *names: str) -> "DenseRDD":
        """Project a subset of columns (narrow, fused). Selecting a wide
        (two-column int64) column — key or value — implicitly keeps its
        low-word partner: the two columns are one logical column."""
        schema = dict(self._schema())
        for n in names:
            if n not in schema:
                raise VegaError(f"no such column: {n!r}")
            if block_lib.is_lo(n) and n[:-len(block_lib.LO_SUFFIX)] \
                    not in names:
                # An orphaned low word decodes to nothing on host reads —
                # data would silently vanish.
                raise VegaError(
                    f"{n!r} is the low word of a wide int64 column; "
                    f"select {n[:-len(block_lib.LO_SUFFIX)]!r} instead "
                    "(the pair travels together)"
                )
        expanded = []
        for n in names:
            expanded.append(n)
            lo = block_lib.lo_of(n)
            if lo in schema and lo not in names:
                expanded.append(lo)
        return _SelectRDD(self, tuple(expanded))

    def rename(self, mapping: dict) -> "DenseRDD":
        """Rename value columns (narrow, fused). A wide int64 column's low
        word travels with it. rename({'w': VALUE}) is the named->canonical
        bridge that re-opens host fallbacks and lv/rv joins for blocks
        built with user column names."""
        schema = dict(self._schema())
        full = {}
        for old, new in mapping.items():
            if old not in schema:
                raise VegaError(f"no such column: {old!r}")
            if old in (KEY, KEY_LO) or new in (KEY, KEY_LO):
                raise VegaError(
                    "the key columns cannot be renamed (or renamed onto): "
                    "a value column renamed to the key name would fabricate "
                    "a pair RDD out of non-key data")
            if block_lib.is_lo(old) or block_lib.is_lo(new):
                raise VegaError(
                    f"the {block_lib.LO_SUFFIX!r} suffix is reserved for "
                    "wide int64 low words; rename the base column instead")
            full[old] = new
            if block_lib.lo_of(old) in schema:
                full[block_lib.lo_of(old)] = block_lib.lo_of(new)
        out_names = [full.get(nm, nm) for nm in schema]
        if len(set(out_names)) != len(out_names):
            raise VegaError(f"rename would collide columns: {out_names}")
        return _RenameRDD(self, full)

    def to_rdd(self) -> RDD:
        """Explicit hand-off to the host tier (identity view)."""
        from vega_tpu.rdd.narrow import MapPartitionsRDD

        return MapPartitionsRDD(self, lambda _i, it: it)

    # --- narrow ops ---------------------------------------------------------
    def _dict_row_gate(self) -> None:
        """Raise _NotTraceable when any column is dictionary-encoded: a
        traced row closure would see int32 codes where the user wrote
        string logic (silently wrong results — codes are private to the
        device tier). The host fallback sees decoded strings, so the
        normal two-tier contract covers strings too."""
        d = self._dicts()
        if d:
            raise _NotTraceable(
                f"dictionary-encoded (string) columns {sorted(d)}; row "
                "closures see decoded strings on the host tier"
            )

    def map(self, f: Callable):
        """Vectorized per-row map if f is traceable, else host fallback
        (the two-tier contract, SURVEY.md §7 hard part 2)."""
        try:
            self._dict_row_gate()
            return _MapRDD(self, f)
        except _NotTraceable as e:
            log.info("dense map fell back to host tier: %s", e)
            return super().map(f)

    def filter(self, predicate: Callable):
        try:
            self._dict_row_gate()
            return _FilterRDD(self, predicate)
        except _NotTraceable as e:
            log.info("dense filter fell back to host tier: %s", e)
            return super().filter(predicate)

    def key_by(self, f: Callable):
        return self.map(lambda x: (f(x), x))

    def map_expand(self, f: Callable, factor: int):
        """Static-arity flat_map: f maps one row to `factor` output rows
        (returned as length-`factor` arrays / tuples of arrays). The fixed
        expansion keeps shapes static — the XLA-compatible subset of
        flat_map (dynamic-arity flat_map falls back to the host tier
        automatically via the normal RDD method)."""
        try:
            self._dict_row_gate()
            return _MapExpandRDD(self, f, factor)
        except _NotTraceable as e:
            log.info("dense map_expand fell back to host tier: %s", e)

            def expand(x):
                out = f(x)
                if isinstance(out, tuple):
                    cols = [np.asarray(o).tolist() for o in out]
                    return list(zip(*cols))
                return np.asarray(out).tolist()

            return super().flat_map(expand)

    def flat_map_ragged(self, f: Callable, max_out_per_row: int):
        """Variable-arity flat_map that stays on device: f maps one row to
        (out, n_valid) — out a (max_out_per_row,) array (or a (keys,
        values) pair of them), n_valid how many lead entries are real.
        This is the XLA-compatible form of the reference's fully-dynamic
        flat_map (rdd.rs:207-214): the per-row bound keeps shapes static;
        genuinely unbounded closures use .flat_map (host tier)."""
        try:
            self._dict_row_gate()
            return _FlatMapRaggedRDD(self, f, max_out_per_row)
        except _NotTraceable as e:
            log.info("dense flat_map_ragged fell back to host tier: %s", e)

            def expand(x):
                out, n = f(x)
                # Same clamp as the device path: host and device results
                # must be identical, only placement may differ.
                n = max(0, min(int(n), max_out_per_row))
                if isinstance(out, tuple):
                    ks, vs = (np.asarray(o)[:n] for o in out)
                    return list(zip(ks.tolist(), vs.tolist()))
                return np.asarray(out)[:n].tolist()

            return super().flat_map(expand)

    def zip(self, other):
        """Dense-dense zip of single-value-column RDDs: per-shard column
        concatenation when shard counts line up (host semantics:
        rdd.rs:818-829); pair / multi-column operands use the host path so
        elements keep their full structure."""
        if (isinstance(other, DenseRDD) and other.mesh == self.mesh
                and [n for n, _ in self._schema()] == [VALUE]
                and [n for n, _ in other._schema()] == [VALUE]):
            return _DenseZipRDD(self, other)
        return RDD.zip(self, other)

    def zip_with_index(self):
        """(value, global index) — the index offsets come from a tiny
        counts transfer at materialization; no second data pass (the host
        tier needs a full counting job, base.py zip_with_index)."""
        if self.is_pair:
            raise VegaError("zip_with_index on pair DenseRDD — use values()")
        if self._wide_value():
            # the wide pair would become a wide KEY with dropped low word
            return RDD.zip_with_index(self)
        return _ZipWithIndexRDD(self)

    def map_values(self, f: Callable):
        if not self.is_pair:
            raise VegaError("map_values on non-pair DenseRDD")
        # Collapse wide (name, name.lo) int64 pairs to ONE logical column
        # each, so user-facing counts and error messages never leak the
        # internal .lo encoding as a phantom second column.
        names = [nm for nm, _ in self._schema()]
        wide_los = set(block_lib.wide_value_pairs(names).values())
        value_names = [nm for nm in names
                       if nm not in (KEY, KEY_LO) and nm not in wide_los]
        if value_names == [VALUE] and block_lib.lo_of(VALUE) in wide_los:
            # Wide int64 VALUE: no traced row form, but the canonical
            # pair layout decodes to (k, v) rows — silent host fallback,
            # the two-tier contract.
            log.info("dense map_values fell back to host tier: wide "
                     "int64 value column")
            return super().map_values(f)
        if len(value_names) != 1:
            # Named/multi-column blocks (wide or not) have no host (k, v)
            # row form — the documented crisp-error exception.
            raise VegaError(
                "map_values needs exactly one value column (have "
                f"{value_names}); use select(...) or a tuple-valued "
                "reduce_by_key on multi-column blocks"
            )
        if value_names[0] in self._dicts():
            if value_names == [VALUE]:
                # Dictionary-encoded (string) VALUE: a traced f would see
                # int32 codes, not strings; the canonical pair layout
                # decodes to (k, v) rows — silent host fallback.
                log.info("dense map_values fell back to host tier: "
                         "dictionary-encoded (string) value column")
                return super().map_values(f)
            raise VegaError(
                f"map_values over dictionary-encoded (string) column "
                f"{value_names[0]!r} on a named block has no device trace "
                f"or host row form; rename({{{value_names[0]!r}: "
                f"{VALUE!r}}}) to the canonical layout for the host "
                "fallback"
            )
        if value_names[0] in block_lib.wide_value_pairs(names):
            # ONE named wide column: a traced f would see only the hi
            # word, and a named block has no host (k, v) row form to fall
            # back on — crisp, naming the one logical column.
            raise VegaError(
                f"map_values over wide int64 column {value_names[0]!r} on "
                "a named block has no device trace or host row form; "
                f"rename({{{value_names[0]!r}: {VALUE!r}}}) to the "
                "canonical layout for the host fallback"
            )
        try:
            return _MapValuesRDD(self, f)
        except _NotTraceable as e:
            log.info("dense map_values fell back to host tier: %s", e)
            return super().map_values(f)

    # --- shuffles -----------------------------------------------------------
    def reduce_by_key(self, func=None, partitioner_or_num=None, *,
                      op: Optional[str] = None,
                      exchange: Optional[str] = None):
        """Device shuffle: pre-combine, all_to_all, segment-reduce.
        `op` in {'add','min','max','prod'} takes the XLA segment fast path;
        a traceable binary `func` uses the segmented associative scan.
        partitioner_or_num is accepted for API parity; dense output is always
        one partition per mesh shard.

        Dtype contract: int64 values use the wide (hi, lo) encoding and
        op='add' tracks signed overflow on device (kernels.wide_add_checked
        flags ride the exchange like capacity flags). A set flag routes to
        a host-exact fold: totals that fit int64 are rebuilt densely
        (transient wraps under reassociation are harmless — mod-2^64
        results equal exact totals whenever they fit), totals beyond int64
        raise a crisp VegaError pointing at the host tier, which keeps
        exact Python bignums. op='add' and an untraceable lambda a, b:
        a + b therefore agree wherever both are representable."""
        if not self.is_pair:
            raise VegaError("reduce_by_key on non-pair DenseRDD")
        if op is None and func is None:
            raise TypeError("need func or op")
        if op is None:
            inferred = _infer_named_op(func)
            if inferred is not None:
                op = inferred
            if op == "prod" and block_lib.wide_value_pairs(
                    nm for nm, _ in self._schema()):
                # A multiplication CLOSURE over wide int64 values: the
                # named path would reject it crisply, but the user gave a
                # closure, so the fallback contract applies — let the
                # func path raise _NotTraceable and fold on the host.
                op = None
        dict_vals = sorted(nm for nm in self._dicts()
                           if nm not in (KEY, KEY_LO))
        if dict_vals and op not in ("min", "max"):
            # Codes are RANK codes, so min/max of codes == lexicographic
            # min/max of the strings (one dictionary per lineage; binary
            # ops unify first) and those folds stay on device. Any other
            # fold (add/prod/closure) would compute on the code VALUES —
            # no string meaning — so host semantics apply (e.g. '+'
            # concatenates strings there).
            plain = {nm for nm, _ in self._schema()
                     if not block_lib.is_lo(nm)}
            if plain != {KEY, VALUE}:
                raise VegaError(
                    "reduce_by_key over dictionary-encoded (string) value "
                    f"columns {dict_vals} needs op='min'/'max' (codes are "
                    "rank codes; other folds have no string meaning on "
                    "device), and a named/multi-column block has no host "
                    "row form to fall back on"
                )
            log.info("dense reduce_by_key fell back to host tier: "
                     "dictionary-encoded (string) value column under "
                     "op=%s", op)
            import operator

            host_func = func if func is not None else \
                {"add": operator.add, "prod": operator.mul}[op]
            return super().reduce_by_key(host_func, partitioner_or_num)
        if op is not None:
            return _with_exchange(_ReduceByKeyRDD(self, op=op, func=None),
                                  exchange)
        try:
            return _with_exchange(_ReduceByKeyRDD(self, op=None, func=func),
                                  exchange)
        except _NotTraceable as e:
            plain = {nm for nm, _ in self._schema()
                     if not block_lib.is_lo(nm)}
            if plain != {KEY, VALUE}:
                # Named/multi-column blocks have no host-tier row form a
                # binary func could fold (compute() yields schema-order
                # tuples, not (k, v) pairs) — the silent fallback would
                # produce WRONG results, so this is the documented
                # exception to the fallback-never-error contract. (Wide
                # keys/values are fine: they decode to (k, v) rows.)
                raise VegaError(
                    "reduce_by_key over a named/multi-column block needs a "
                    f"traceable binop (not traceable: {e}); use "
                    "op='add'/'min'/'max'/'prod' or a traceable tuple binop"
                ) from e
            log.info("dense reduce_by_key fell back to host tier: %s", e)
            return super().reduce_by_key(func, partitioner_or_num)

    def sum_by_key(self):
        return self.reduce_by_key(op="add")

    def count_by_key_dense(self):
        """(key, occurrence count) pairs. Works on any keyed block — pair,
        key-only (a bare key column is a valid thing to count), and
        named/multi-column — by synthesizing a ones column and riding the
        named-op exchange; no traced user closure involved."""
        if not self.is_pair:
            raise VegaError("count_by_key_dense on un-keyed DenseRDD")
        return _OnesValueRDD(self).reduce_by_key(op="add")

    def combine_by_key(self, create_combiner: Callable,
                       merge_value: Callable, merge_combiners: Callable,
                       partitioner_or_num=None, *,
                       exchange: Optional[str] = None):
        """Device combine_by_key for scalar traceable combiners
        (reference: pair_rdd.rs:20-33): lowered to
        map_values(create_combiner) + segment-reduce(merge_combiners),
        which equals the host semantics under the standard combiner
        compatibility contract merge_value(c, v) ==
        merge_combiners(c, create_combiner(v)). Untraceable or non-scalar
        combiners fall back to the host tier DIRECTLY (the host mixin's
        own reduce_by_key lowers through self.combine_by_key, so the
        fallback must not re-dispatch through this override)."""
        if not self.is_pair:
            raise VegaError("combine_by_key on non-pair DenseRDD")
        if block_lib.wide_value_pairs(nm for nm, _ in self._schema()) or \
                any(nm not in (KEY, KEY_LO) for nm in self._dicts()):
            # Wide int64 values: _MapValuesRDD would trace create_combiner
            # over the hi word alone and silently drop the low word.
            # Dictionary-encoded (string) values: the combiner would see
            # int32 codes, not strings. Either way no device trace -> host
            # tier (exact int64 / decoded-string combiners).
            log.info("dense combine_by_key fell back to host tier: wide "
                     "int64 or dictionary-encoded value column")
            from vega_tpu.rdd.pair import PairOpsMixin

            return PairOpsMixin.combine_by_key(
                self, create_combiner, merge_value, merge_combiners,
                partitioner_or_num,
            )
        try:
            mapped = _MapValuesRDD(self, create_combiner)
            op = _infer_named_op(merge_combiners)
            node = _ReduceByKeyRDD(mapped, op=op,
                                   func=None if op else merge_combiners)
            return _with_exchange(node, exchange)
        except _NotTraceable as e:
            log.info("dense combine_by_key fell back to host tier: %s", e)
            from vega_tpu.rdd.pair import PairOpsMixin

            return PairOpsMixin.combine_by_key(
                self, create_combiner, merge_value, merge_combiners,
                partitioner_or_num,
            )

    # fold_by_key / aggregate_by_key deliberately have NO device lowering:
    # their zero is applied once per key per PARTITION (host tier,
    # rdd/pair.py:74-93 — our extension; the reference has neither op), and
    # that partition-coupled semantic is not expressible as an associative
    # device combine without silently changing results for non-neutral
    # zeros. For the device path, express the job as
    # map_values(...) + reduce_by_key(op=...) explicitly.

    def group_by_key(self, partitioner_or_num=None,
                     exchange: Optional[str] = None):
        """Device group_by_key: exchange by key hash, sort within shard.
        The result block holds sorted runs; collect() reassembles the
        (key, [values]) lists on the host — the dense analogue of the
        reference's Vec-collecting aggregator (aggregator.rs:33-53)."""
        if not self.is_pair:
            raise VegaError("group_by_key on non-pair DenseRDD")
        return _with_exchange(_GroupByKeyRDD(self), exchange)

    def join(self, other, partitioner_or_num=None,
             exchange: Optional[str] = None):
        """Device sort-merge join with full duplicate-key semantics (dup x
        dup product per key, reference pair_rdd.rs:104-121). Falls back to
        the host cogroup-based join only when `other` is not dense or an
        explicit partitioner is requested."""
        if self._dense_joinable(other, partitioner_or_num):
            pair = _align_keys(self, other)
            if pair is not None:
                return _with_exchange(_JoinRDD(*pair), exchange)
        self._reject_named_join([other], "join")
        return super().join(other, partitioner_or_num)

    def _dense_joinable(self, other, partitioner_or_num) -> bool:
        """Same preconditions as the dense cogroup: both dense pairs, no
        explicit partitioner request, one mesh (mismatched meshes would pair
        unrelated shards), and BOTH sides in the canonical value layout —
        the join kernel names its outputs lv/rv, so a named/multi-column
        side would come out mangled (see _reject_named_join)."""
        return (isinstance(other, DenseRDD) and self.is_pair and other.is_pair
                and partitioner_or_num is None and other.mesh == self.mesh
                and _canonical_value_layout(self._schema())
                and _canonical_value_layout(other._schema()))

    def _reject_named_join(self, others, op: str) -> None:
        """Named/multi-column pair blocks can reach neither the dense join
        (its lv/rv output contract is (k, (lv, rv)) rows) nor the host
        cogroup fallback (named blocks have no host-tier (k, v) row form)
        — the documented crisp-error exception to the silent-fallback
        contract, same as reduce_by_key's untraceable-binop case."""
        for label, side in [("left", self)] + [("right", o) for o in others]:
            if (isinstance(side, DenseRDD) and side.is_pair
                    and not _canonical_value_layout(side._schema())):
                raise VegaError(
                    f"{op} over a named/multi-column DenseRDD ({label} side"
                    f" columns {[nm for nm, _ in side._schema()]}) has no"
                    " (k, v) row form on either tier; select(...) down to"
                    f" one value column and rename(...) it to {VALUE!r}"
                    " first"
                )

    def left_outer_join(self, other, partitioner_or_num=None,
                        fill_value=0, exchange: Optional[str] = None):
        """Device left-outer join (duplicate keys allowed on both sides):
        unmatched left rows keep fill_value in the right column (None is
        not representable
        in a dense column — host semantics with None come via
        .to_rdd().left_outer_join(...)). The host fallback also honors
        fill_value so results don't depend on which path ran."""
        wide_right = isinstance(other, DenseRDD) and other.is_pair and \
            block_lib.wide_value_pairs(nm for nm, _ in other._schema())
        dict_right = isinstance(other, DenseRDD) and other.is_pair and \
            any(nm not in (KEY, KEY_LO) for nm in other._dicts())
        if fill_value is not None and not wide_right and not dict_right \
                and self._dense_joinable(other, partitioner_or_num):
            # wide_right gate: the kernel fills unmatched right columns
            # with one scalar per column, which would land RAW in the
            # encoded (hi, lo) words and decode to garbage — the host
            # path fills the real int64. dict_right likewise: the fill
            # scalar would land in the CODE column and decode to an
            # arbitrary dictionary string instead of fill_value.
            pair = _align_keys(self, other)
            if pair is not None:
                return _with_exchange(
                    _JoinRDD(*pair, outer=True, fill_value=fill_value),
                    exchange,
                )
        self._reject_named_join([other], "left_outer_join")
        if fill_value is None:
            # Host None semantics (a dense column can't hold None).
            return super().left_outer_join(other, partitioner_or_num)
        # Host fallback with fill: emit per GROUP so a legitimate None right
        # value is never conflated with "unmatched".

        def emit(groups):
            lvs, rvs = groups
            if not rvs:
                return [(lv, fill_value) for lv in lvs]
            return [(lv, rv) for lv in lvs for rv in rvs]

        return self.cogroup(
            other, partitioner_or_num=partitioner_or_num
        ).flat_map_values(emit)

    def cogroup(self, *others, partitioner_or_num=None):
        """Dense-dense cogroup: both sides exchange + sort on device (hash
        placement is shared, so co-keyed rows land on the same shard); only
        the ragged (k, ([lvs], [rvs])) assembly happens on the host.
        Reference semantics: pair_rdd.rs:123-155 / co_grouped_rdd.rs."""
        if len(others) == 1 and self._dense_joinable(others[0],
                                                     partitioner_or_num):
            # An explicit partitioner request or a mesh mismatch must honor
            # host-path semantics (and mismatched meshes would pair
            # unrelated shards) — those fall through to the host cogroup.
            # Key widths/dtypes must align so co-keyed rows share a shard.
            pair = _align_keys(self, others[0])
            if pair is not None:
                return _DenseCoGroupRDD(*pair)
        self._reject_named_join(others, "cogroup")
        return super().cogroup(*others, partitioner_or_num=partitioner_or_num)

    def cartesian(self, other):
        """Device cross product (BASELINE config 4; reference
        cartesian_rdd.rs): the right side replicates to every shard and
        each shard ragged-expands its left rows against it — one program,
        no collectives beyond the replication. Products too big for the
        HBM budget (or non-dense/multi-column operands) use the host
        tier's lazy cartesian, which streams instead of materializing."""
        from vega_tpu.env import Env

        if (isinstance(other, DenseRDD) and other.mesh == self.mesh
                and [n for n, _ in self._schema()] == [VALUE]
                and [n for n, _ in other._schema()] == [VALUE]
                and not self._dicts() and not other._dicts()):
            # dict gate: the kernel snapshots the right side via
            # to_numpy(), which decodes strings — re-staging them on
            # device has no form. The host tier streams decoded rows.
            budget = getattr(Env.get().conf, "dense_hbm_budget", 4 << 30)
            try:
                return _CartesianDenseRDD(self, other, budget)
            except _NotTraceable as e:
                log.info("dense cartesian fell back to host tier: %s", e)
        return RDD.cartesian(self, other)

    def sort_by_key(self, ascending: bool = True, num_partitions=None,
                    sample_size_hint: int = 4096,
                    exchange: Optional[str] = None):
        """Distributed sample sort: driver samples keys, computes range
        bounds, range-exchange, local sort (BASELINE config 5)."""
        if not self.is_pair:
            raise VegaError("sort_by_key on non-pair DenseRDD")
        return _with_exchange(_SortByKeyRDD(self, ascending, sample_size_hint),
                              exchange)

    def distinct(self, num_partitions=None):
        if self.is_pair:
            return super().distinct(num_partitions)
        keyed = _MapRDD(self, lambda v: (v, jnp.int32(0)))
        # Trusted internal closure: the value moves to the key unchanged,
        # so dict-ness (string codes) follows it — dedup by code == dedup
        # by string within one lineage's dictionary.
        keyed._dict_renames = {KEY: VALUE}
        return _ReduceByKeyRDD(keyed, op="min", func=None).keys_dense()

    def _dense_set_op_ok(self, other) -> bool:
        """Device set ops need value RDDs on one mesh with MATCHING value
        dtypes: an int32 2 and a float32 2.0 hash to different buckets on
        device but compare equal on the host, so mismatched dtypes must
        take the host path (Python equality semantics), never silently
        miss matches."""
        return (isinstance(other, DenseRDD) and other.mesh == self.mesh
                and not self.is_pair and not other.is_pair
                and dict(self._schema())[VALUE]
                == dict(other._schema())[VALUE])

    def intersection(self, other, num_partitions=None):
        """Device set intersection of value RDDs: each side dedups
        through a keyed reduce (output hash-placed and key-sorted, so the
        join elides BOTH exchanges and sorts), then keeps the joined keys
        (reference semantics: rdd.rs:831-841, deduplicated)."""
        if self._dense_set_op_ok(other):
            pair = _unify_dict_cols(self, other, (VALUE,))
            if pair is None:  # dict-ness mismatch: only host equality holds
                return RDD.intersection(self, other, num_partitions)
            left, right = pair

            def dedup(side):
                keyed = _MapRDD(side, lambda v: (v, jnp.int32(0)))
                keyed._dict_renames = {KEY: VALUE}  # value moves to key
                return _ReduceByKeyRDD(keyed, op="min", func=None)

            return _JoinRDD(dedup(left), dedup(right)).keys_dense()
        return RDD.intersection(self, other, num_partitions)

    def subtract(self, other, num_partitions=None):
        """Device set subtraction: keep self's elements (duplicates
        included) whose value never appears in `other` — a left outer
        join against other's deduped values with an unambiguous marker
        (right values are all 1; fill is 0), filtered on the device.
        The marks side is a reduce output, so its exchange elides
        (reference semantics: rdd.rs:843-870)."""
        if self._dense_set_op_ok(other):
            pair = _unify_dict_cols(self, other, (VALUE,))
            if pair is None:  # dict-ness mismatch: only host equality holds
                return RDD.subtract(self, other, num_partitions)
            left, right = pair
            keyed = _MapRDD(left, lambda v: (v, jnp.int32(1)))
            keyed._dict_renames = {KEY: VALUE}  # value moves to key
            marked = _MapRDD(right, lambda v: (v, jnp.int32(1)))
            marked._dict_renames = {KEY: VALUE}
            marks = _ReduceByKeyRDD(marked, op="min", func=None)
            joined = _JoinRDD(keyed, marks, outer=True, fill_value=0)
            # Trusted internal predicate: it reads only the int32 mark
            # column, so construct _FilterRDD directly — the public
            # filter's dict gate would see the (possibly dict-encoded)
            # KEY and needlessly force the host tier.
            return _FilterRDD(
                joined.select(KEY, "rv"), lambda row: row[1] == 0
            ).keys_dense()
        return RDD.subtract(self, other, num_partitions)

    def keys_dense(self):
        if KEY_LO in dict(self._schema()):
            # int64 keys cannot live in a single device value column;
            # hand off to the host tier (decoded rows).
            return self.to_rdd().map(lambda kv: kv[0])
        return _ProjectRDD(self, KEY)

    def values_dense(self):
        if self._wide_value():
            # keep the wide pair on device: select() carries the low-word
            # partner, yielding a keyless wide block (named reductions
            # fold it on device; closures fall back to decoded rows)
            return self.select(VALUE)
        return _ProjectRDD(self, VALUE)

    # --- actions ------------------------------------------------------------
    def count(self) -> int:
        return self.block().num_rows

    def collect(self) -> list:
        cols = self.block().to_numpy()
        names = list(cols)
        if names == [VALUE]:
            return cols[VALUE].tolist()
        if set(names) == {KEY, VALUE}:
            return list(zip(cols[KEY].tolist(), cols[VALUE].tolist()))
        return list(zip(*[cols[n].tolist() for n in names]))

    def collect_arrays(self) -> dict:
        """Columnar collect — no per-row Python objects."""
        return self.block().to_numpy()

    def _wide_value(self) -> bool:
        """True when VALUE is a wide (two-column int64) encoding."""
        return block_lib.lo_of(VALUE) in dict(self._schema())

    def sum(self):
        return self._named_reduce("add")

    def min(self):
        return self._named_reduce("min")

    def max(self):
        return self._named_reduce("max")

    def mean(self):
        n = self.count()
        if n == 0:
            raise VegaError("mean of empty DenseRDD")
        return self.sum() / n

    def reduce(self, f: Callable):
        """Arbitrary traceable binop: per-shard segmented reduce on device,
        tiny cross-shard fold on the driver (two-level reduction,
        SURVEY.md §7 step 3; host-tier semantics rdd.rs:274-309)."""
        blk = self.block()
        col = VALUE if not self.is_pair else None
        if col is None:
            return super().reduce(f)  # pairs: host semantics
        if self._wide_value():
            # No scalar row form for wide int64 — host fold sees the
            # decoded int64s (and keeps exact bignum arithmetic).
            return super().reduce(f)
        if VALUE in self._dicts():
            # Dictionary-encoded strings: the traced binop would fold
            # int32 codes — host fold sees the decoded strings.
            return super().reduce(f)
        cap = blk.capacity

        def shard_reduce(vals, counts):
            count = counts[0]
            cols = {VALUE: vals}
            combine = lambda a, b: {VALUE: f(a[VALUE], b[VALUE])}
            # Single segment: constant key over valid rows.
            keyed = dict(cols)
            keyed["__k"] = jnp.zeros((cap,), jnp.int32)
            out, n_out = kernels.segment_reduce_sorted(
                keyed, count, "__k", combine, presorted=True
            )
            return out[VALUE][:1], (n_out > 0).reshape(1)

        prog = _cached_program(
            ("reduce", self.mesh, _fp(f)),
            lambda: _shard_program(self.mesh, shard_reduce, 2, (_SPEC, _SPEC)),
        )
        partials, flags = prog(blk.cols[VALUE], blk.counts)
        partials, flags = mesh_lib.host_get((partials, flags))  # one RTT
        partials, flags = np.asarray(partials), np.asarray(flags)
        vals = [partials[i] for i in range(len(flags)) if flags[i]]
        if not vals:
            raise VegaError("reduce() of empty RDD")
        acc = vals[0]
        for x in vals[1:]:
            acc = np.asarray(f(acc, x))
        return acc.item() if acc.ndim == 0 else acc

    def _named_reduce(self, op: str):
        vdict = self._dicts().get(VALUE)
        if vdict is not None and op == "add":
            # A sum of dictionary codes has no string meaning, and there
            # is no host sum of strings either — crisp, not silent.
            raise VegaError(
                "sum() over a string (dictionary-encoded) column has no "
                "meaning; min()/max() are the defined string reductions"
            )
        blk = self.block()
        if self.is_pair:
            raise VegaError(f"{op}() on pair DenseRDD — reduce values instead")
        if block_lib.lo_of(VALUE) in blk.cols:
            return self._named_reduce_wide(op, blk)

        def shard_fn(vals, counts):
            partial = kernels.masked_reduce(vals, counts[0], op)
            return partial.reshape((1,) + partial.shape)

        prog = _cached_program(
            ("named_reduce", self.mesh, op),
            lambda: _shard_program(self.mesh, shard_fn, 2, _SPEC),
        )
        partials = np.asarray(mesh_lib.host_get(prog(blk.cols[VALUE], blk.counts)))
        if op == "add":
            return partials.sum(axis=0).item()
        code = (partials.min(axis=0) if op == "min"
                else partials.max(axis=0)).item()
        if vdict is not None:
            # min/max of rank codes == lexicographic min/max; decode the
            # winning code back to its string at this collect boundary.
            # An out-of-range code is the masked_reduce identity sentinel:
            # every row was padding.
            if not 0 <= code < len(vdict):
                raise VegaError(f"{op}() of empty DenseRDD")
            return vdict[code].item()
        return code

    def _named_reduce_wide(self, op: str, blk: Block):
        """sum/min/max over a wide (two-column int64) keyless VALUE: one
        per-shard device fold with the same carry/lex combine the keyed
        exchanges use, then an exact Python fold over the n_shards
        partials on the driver. add partials carry the sticky overflow
        flag (kernels.wide_add_checked) — a flagged shard's partial may
        have wrapped, so the driver refolds exactly from the decoded
        rows. Actions return Python ints, so even beyond-int64 totals
        come back exact (host-tier semantics)."""
        vlo = block_lib.lo_of(VALUE)
        track = op == "add"

        def shard_fold(hi, lo, counts):
            count = counts[0]
            cap = hi.shape[0]
            keyed = {"__k": jnp.zeros((cap,), jnp.int32), VALUE: hi,
                     vlo: lo}
            names = [VALUE, vlo]
            if track:
                keyed[_SOVF] = jnp.zeros((cap,), jnp.int32)
                names.append(_SOVF)
            combine = _named_wide_combine(
                op, names, {VALUE: vlo},
                ovf_name=_SOVF if track else None)
            out, n_out = kernels.segment_reduce_sorted(
                keyed, count, "__k", combine, presorted=True)
            flag = out[_SOVF][:1] if track else jnp.zeros((1,), jnp.int32)
            return (out[VALUE][:1], out[vlo][:1], flag,
                    (n_out > 0).reshape(1).astype(jnp.int32))

        prog = _cached_program(
            ("named_reduce_wide", self.mesh, op),
            lambda: _shard_program(self.mesh, shard_fold, 3, (_SPEC,) * 4),
        )
        his, los, flags, nonempty = (
            np.asarray(x) for x in mesh_lib.host_get(
                prog(blk.cols[VALUE], blk.cols[vlo], blk.counts)))
        valid = nonempty.reshape(-1) != 0
        partials = block_lib.decode_i64(his.reshape(-1), los.reshape(-1))
        if op == "add":
            if np.any(flags.reshape(-1)[valid]):
                # some shard partial wrapped int64: exact host refold
                col = blk.to_numpy()[VALUE]
                return sum(int(x) for x in col.tolist())
            return sum(int(x) for x in partials[valid])
        picked = partials[valid]
        if picked.size == 0:
            raise VegaError(f"{op}() of empty DenseRDD")
        return int(picked.min()) if op == "min" else int(picked.max())

    def sample(self, with_replacement: bool, fraction: float,
               seed: Optional[int] = None):
        """Device-side Bernoulli sampling (per-shard threefry stream,
        host-tier analogue: utils/random.py BernoulliSampler). Poisson
        (with-replacement) sampling falls back to the host tier."""
        if with_replacement:
            return RDD.sample(self, True, fraction, seed)
        return _SampleRDD(self, fraction, seed or 0)

    def union(self, other):
        """Dense-dense union: per-shard block concatenation in one program;
        anything else falls back to the host UnionRDD."""
        if isinstance(other, DenseRDD) and \
                dict(self._schema()) == dict(other._schema()):
            names = tuple(nm for nm, _ in self._schema())
            pair = _unify_dict_cols(self, other, names)
            if pair is None:  # dict-ness mismatch: host rows compare right
                return RDD.union(self, other)
            return _DenseUnionRDD(*pair)
        return RDD.union(self, other)

    def count_by_value(self) -> dict:
        """Device count_by_value: value->key exchange + segment count
        (host semantics: rdd.rs:450-464)."""
        if self.is_pair or self._wide_value():
            # wide: no scalar row form for the value->key map closure
            return RDD.count_by_value(self)
        keyed = _MapRDD(self, lambda x: (x, jnp.int32(1)))
        # Trusted internal closure: the value moves to the key unchanged,
        # so dict-ness follows it; counts per code == counts per string,
        # and collect() decodes the keys.
        keyed._dict_renames = {KEY: VALUE}
        return dict(_ReduceByKeyRDD(keyed, op="add", func=None).collect())

    def take_ordered(self, n: int, key=None) -> list:
        """Smallest n via per-shard lax.top_k (values) or masked row sort
        (pairs, ordered like host tuples: key then values) + driver merge
        (host analogue: BoundedPriorityQueue, rdd.rs:1124-1153). Custom key
        functions fall back to the host path — closures don't trace into
        an ordering."""
        if key is not None:
            return RDD.take_ordered(self, n, key)
        if self.is_pair or self._wide_value():
            # wide int64 values: the row sort orders the adjacent
            # (VALUE, VALUE.lo) pair lexicographically == int64 order
            return self._device_topk_rows(n, largest=False)
        return self._device_topk(n, largest=False)

    def top(self, n: int, key=None) -> list:
        if key is not None:
            return RDD.top(self, n, key)
        if self.is_pair or self._wide_value():
            return self._device_topk_rows(n, largest=True)
        return self._device_topk(n, largest=True)

    def _device_topk(self, n: int, largest: bool) -> list:
        blk = self.block()
        k = min(n, blk.capacity)

        def shard_topk(vals, counts):
            mask = kernels.valid_mask(vals.shape[0], counts[0])
            if largest:
                if jnp.issubdtype(vals.dtype, jnp.floating):
                    lo = jnp.array(-jnp.inf, vals.dtype)
                else:
                    lo = jnp.array(jnp.iinfo(vals.dtype).min, vals.dtype)
                masked = jnp.where(mask, vals, lo)
                best, _ = lax.top_k(masked, k)
            else:
                hi = kernels._orderable_max(vals)
                masked = jnp.where(mask, vals, hi)
                if jnp.issubdtype(vals.dtype, jnp.floating):
                    best = -lax.top_k(-masked, k)[0]
                else:
                    # Bitwise complement is an overflow-free order flip for
                    # ints (arithmetic negation wraps on iinfo.min).
                    best = ~lax.top_k(~masked, k)[0]
            n_valid = jnp.minimum(counts[0], k)
            return best, n_valid.reshape(1)

        prog = _cached_program(
            ("topk", self.mesh, k, largest),
            lambda: _shard_program(self.mesh, shard_topk, 2, (_SPEC, _SPEC)),
        )
        best, n_valid = prog(blk.cols[VALUE], blk.counts)
        best, n_valid = mesh_lib.host_get((best, n_valid))  # one RTT
        best = np.asarray(best).reshape(blk.n_shards, k)
        n_valid = np.asarray(n_valid)
        candidates = np.concatenate(
            [best[s, : n_valid[s]] for s in range(blk.n_shards)]
        ) if blk.n_shards else np.empty((0,))
        candidates = np.sort(candidates)
        if largest:
            candidates = candidates[::-1]
        vdict = self._dicts().get(VALUE)
        if vdict is not None:
            # Rank codes ordered == strings ordered; decode the survivors
            # at this collect boundary.
            candidates = vdict[candidates.astype(np.int64)]
        return candidates[:n].tolist()

    def _device_topk_rows(self, n: int, largest: bool) -> list:
        """First/last n ROWS in natural element order — the order of the
        tuples collect() emits (schema order; for the canonical pair
        block that is (key, value), matching the host tier's tuple
        ordering). Guarantees sorted(collect())[:n] == take_ordered(n)
        whatever the schema. Per shard: one stable lax.sort over
        (validity, every column), slice n; driver merges the n_shards*n
        survivors with the same lexicographic order. Total-order caveat:
        XLA sorts NaN after +inf; Python's NaN comparisons are unordered,
        so like the host sort the result is only well-defined for
        NaN-free data."""
        blk = self.block()
        names = [nm for nm, _ in self._schema()]
        # Sort operands in schema order: a two-column int64 key sits as
        # adjacent (KEY=hi, KEY_LO=lo) columns, so lexicographic schema
        # order IS int64 order in place.
        k = min(max(n, 1), blk.capacity)
        impl = _sort_impl()
        # radix/packed need every column as an orderable-uint32 word
        use_radix = impl in ("radix", "radix4", "packed") and all(
            jnp.dtype(dt) in (jnp.dtype(jnp.int32), jnp.dtype(jnp.float32))
            for _, dt in self._schema())

        def shard_sorted_radix(counts, *cols):
            count = counts[0]
            # LSD = last schema column
            words = kernels.orderable_words(list(reversed(cols)))
            if impl == "packed":
                order = kernels.packed_sort_perm(words, count,
                                                 descending=largest)
            else:
                order = kernels.radix_sort_perm(
                    words, count, descending=largest,
                    bits=4 if impl == "radix4" else 8)
            n_valid = jnp.minimum(count, k).reshape(1)
            # original (unflipped) values, gathered once
            return (n_valid,) + tuple(jnp.take(c, order[:k]) for c in cols)

        def shard_sorted(counts, *cols):
            capacity = cols[0].shape[0]
            invalid = ~kernels.valid_mask(capacity, counts[0])
            operands = [invalid.astype(jnp.int32)]
            for c in cols:
                if largest:
                    if jnp.issubdtype(c.dtype, jnp.floating):
                        flipped = -c
                    else:
                        flipped = ~c  # overflow-free order reversal
                    # invalid rows must still sink: flag is operand 0
                    operands.append(flipped)
                else:
                    operands.append(c)
            out = lax.sort(tuple(operands), num_keys=len(operands),
                           is_stable=True)
            n_valid = jnp.minimum(counts[0], k).reshape(1)
            return (n_valid,) + tuple(o[:k] for o in out[1:])

        prog = _cached_program(
            ("topk_rows", self.mesh, tuple(names), k, largest,
             tuple(str(dt) for _, dt in self._schema()),
             impl if use_radix else "xla"),
            lambda: _shard_program(
                self.mesh,
                shard_sorted_radix if use_radix else shard_sorted,
                1 + len(names),
                (_SPEC,) * (1 + len(names)),
            ),
        )
        outs = prog(blk.counts, *[blk.cols[nm] for nm in names])
        outs = mesh_lib.host_get(outs)  # one RTT
        n_valid = np.asarray(outs[0]).reshape(-1)
        per_col = [np.asarray(o).reshape(blk.n_shards, k)
                   for o in outs[1:]]
        keep = []
        for s in range(blk.n_shards):
            c = int(n_valid[s])
            if c:
                keep.append([col[s, :c] for col in per_col])
        if not keep:
            return []
        merged = {nm: np.concatenate([rows[i] for rows in keep])
                  for i, nm in enumerate(names)}
        if largest and not use_radix:
            # un-flip (the lax.sort path returned flipped sort operands;
            # the radix path gathers original values)
            for nm in names:
                col = merged[nm]
                merged[nm] = -col if np.issubdtype(col.dtype, np.floating) \
                    else ~col
        merged = block_lib._decode_key_cols(merged)  # schema order kept
        order_cols = list(merged.values())
        # np.lexsort: last key is primary -> reverse; stable like the
        # device sort. Dictionary-encoded columns order by their RANK
        # codes here — identical to string order — and decode below.
        order = np.lexsort([c if not largest else
                            (-c if np.issubdtype(c.dtype, np.floating)
                             else ~c)
                            for c in reversed(order_cols)])
        out_names = [nm for nm in names if not block_lib.is_lo(nm)]
        dicts = self._dicts()
        for nm in out_names:
            if nm in dicts:  # collect boundary: codes -> strings
                merged[nm] = dicts[nm][merged[nm]]
        rows = [tuple(merged[nm][i] for nm in out_names)
                for i in order[:n]]
        if out_names == [KEY, VALUE]:
            return [(k_.item(), v_.item()) for k_, v_ in rows]
        if len(out_names) == 1:  # keyless single column: scalars, not
            return [row[0].item() for row in rows]  # 1-tuples
        return [tuple(x.item() for x in row) for row in rows]

    def stats(self) -> dict:
        """count/mean/stdev/min/max in one device pass (host analogue:
        rdd.rs-adjacent stats; see base.py)."""
        import math

        blk = self.block()
        if self.is_pair or self._wide_value() or VALUE in self._dicts():
            # wide/dict: host sees decoded int64 / string rows (and the
            # host path raises its normal TypeError for string stats)
            return RDD.stats(self)

        def shard_stats(vals, counts):
            count = counts[0]
            v = vals.astype(jnp.float32)
            s = kernels.masked_reduce(v, count, "add")
            ss = kernels.masked_reduce(v * v, count, "add")
            mn = kernels.masked_reduce(v, count, "min")
            mx = kernels.masked_reduce(v, count, "max")
            # Count stays integer (float32 is exact only to 2^24 — a v5e-8
            # shard of the 1B-row target holds ~125M rows).
            return counts.reshape(1), jnp.stack([s, ss, mn, mx]).reshape(1, 4)

        prog = _cached_program(
            ("stats", self.mesh),
            lambda: _shard_program(self.mesh, shard_stats, 2, (_SPEC, _SPEC)),
        )
        int_counts, parts = prog(blk.cols[VALUE], blk.counts)
        int_counts, parts = mesh_lib.host_get((int_counts, parts))  # one RTT
        int_counts = np.asarray(int_counts).reshape(-1)
        parts = np.asarray(parts)
        n = int(int_counts.sum())
        s = float(parts[:, 0].sum())
        ss = float(parts[:, 1].sum())
        valid = int_counts > 0
        mn = float(parts[valid, 2].min()) if valid.any() else float("inf")
        mx = float(parts[valid, 3].max()) if valid.any() else float("-inf")
        mean = s / n if n else float("nan")
        var = max(0.0, ss / n - mean * mean) if n else float("nan")
        return {"count": n, "mean": mean,
                "stdev": math.sqrt(var) if n else float("nan"),
                "min": mn, "max": mx}

    def _min_max(self):
        """Fused single-pass min+max (one device program, not two). Only
        histogram() calls this, and it routes wide-value blocks to the
        host tier first, so this always sees a narrow VALUE column."""
        blk = self.block()

        def shard_mm(vals, counts):
            count = counts[0]
            mn = kernels.masked_reduce(vals, count, "min")
            mx = kernels.masked_reduce(vals, count, "max")
            return jnp.stack([mn, mx]).reshape(1, 2), counts.reshape(1)

        prog = _cached_program(
            ("minmax", self.mesh),
            lambda: _shard_program(self.mesh, shard_mm, 2, (_SPEC, _SPEC)),
        )
        parts, int_counts = prog(blk.cols[VALUE], blk.counts)
        parts, int_counts = mesh_lib.host_get((parts, int_counts))  # one RTT
        parts = np.asarray(parts)
        valid = np.asarray(int_counts).reshape(-1) > 0
        if not valid.any():
            raise VegaError("min/max of empty DenseRDD")
        return parts[valid, 0].min().item(), parts[valid, 1].max().item()

    def histogram(self, buckets):
        """Device histogram: bucketize + per-shard bincount + driver sum."""
        if self.is_pair or self._wide_value() or VALUE in self._dicts():
            # wide: float32 bucketing would mangle int64s; host is exact.
            # dict: bucketing codes is not bucketing strings — the host
            # path raises its normal TypeError for string histograms.
            return RDD.histogram(self, buckets)
        if isinstance(buckets, int):
            lo, hi = self._min_max()
            if lo == hi:
                return [lo, hi], [self.count()]
            step = (hi - lo) / buckets
            edges = [lo + i * step for i in range(buckets)] + [hi]
        else:
            edges = list(buckets)
        n_bins = len(edges) - 1
        blk = self.block()
        edges_dev = mesh_lib.host_put(
            np.asarray(edges, dtype=np.float32),
            mesh_lib.replicated_spec(self.mesh))

        def shard_hist(bnds, vals, counts):
            v = vals.astype(jnp.float32)
            mask = kernels.valid_mask(v.shape[0], counts[0])
            mask = mask & (v >= bnds[0]) & (v <= bnds[-1])
            idx = jnp.clip(jnp.searchsorted(bnds, v, side="right") - 1,
                           0, n_bins - 1)
            idx = jnp.where(mask, idx, n_bins)
            return jnp.bincount(idx, length=n_bins + 1)[:n_bins].reshape(1, -1)

        prog = _cached_program(
            ("hist", self.mesh, n_bins),
            lambda: _shard_program(
                self.mesh, shard_hist, (_REPL, _SPEC, _SPEC), _SPEC
            ),
        )
        parts = np.asarray(mesh_lib.host_get(
            prog(edges_dev, blk.cols[VALUE], blk.counts)
        ))
        return edges, parts.sum(axis=0).tolist()

    def save_npz(self, path: str) -> str:
        """Persist the materialized block's valid rows as one .npz of
        column arrays — the dense analogue of checkpoint(): reloading with
        ctx.dense_load_npz() re-sources the data with no lineage. One file;
        shard layout is reconstructed on load for the current mesh."""
        import os as _os

        if type(self).collect is not DenseRDD.collect:
            raise VegaError(
                "save_npz persists raw columns; this RDD's elements are "
                "derived from them (grouped/joined) — save an upstream RDD "
                "or materialize via collect()/to_rdd() instead"
            )
        blk = self.block()
        cols = blk.to_numpy()  # valid rows only, shard order
        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:  # file object: savez keeps the exact name
            np.savez(f, **cols)
        _os.replace(tmp, path)
        return path

    def take(self, n: int) -> list:
        # Pull shard by shard until satisfied; avoids full collect.
        out = []
        blk = self.block()
        for s in range(blk.n_shards):
            rows = blk.shard_rows(s)
            names = list(rows)
            if names == [VALUE]:
                out.extend(rows[VALUE].tolist())
            elif set(names) == {KEY, VALUE}:
                out.extend(zip(rows[KEY].tolist(), rows[VALUE].tolist()))
            else:
                out.extend(zip(*[rows[n].tolist() for n in names]))
            if len(out) >= n:
                break
        return out[:n]


class _NotTraceable(Exception):
    pass


# ---------------------------------------------------------------------------
# element <-> column conventions
# ---------------------------------------------------------------------------


def _row_struct(schema):
    """Abstract per-row value for tracing: scalar v, or (k, v) pair."""
    cols = dict(schema)
    if any(block_lib.is_lo(nm) for nm in cols):
        # Wide (two-column int64) keys or values have no device row form
        # (the int64 scalar cannot be traced without x64); row-wise
        # closures take the host tier, which sees the reassembled int64s.
        raise _NotTraceable("int64 keys/values: no device row form")
    if set(cols) == {KEY, VALUE}:
        return (jax.ShapeDtypeStruct((), cols[KEY]),
                jax.ShapeDtypeStruct((), cols[VALUE]))
    if set(cols) == {VALUE}:
        return jax.ShapeDtypeStruct((), cols[VALUE])
    return tuple(jax.ShapeDtypeStruct((), dt) for _n, dt in schema)


def _trace_row_fn(f, schema):
    """Introspect f's output structure on abstract rows; returns
    (out_schema, cols_fn) where cols_fn maps column dict -> column dict.
    Raises _NotTraceable for non-jax functions."""
    in_struct = _row_struct(schema)
    try:
        out_struct = jax.eval_shape(f, in_struct)
    except Exception as e:  # noqa: BLE001 — any trace error means host tier
        raise _NotTraceable(str(e)) from e

    def check_scalar(s):
        if s.shape != ():
            raise _NotTraceable(f"row fn must return scalars, got {s.shape}")

    if isinstance(out_struct, tuple) and len(out_struct) == 2:
        for s in out_struct:
            check_scalar(s)
        out_schema = ((KEY, out_struct[0].dtype), (VALUE, out_struct[1].dtype))

        def cols_fn(cols):
            args = _cols_to_row(cols, schema)
            k, v = jax.vmap(f)(args)
            return {KEY: k, VALUE: v}

    elif hasattr(out_struct, "shape"):
        check_scalar(out_struct)
        out_schema = ((VALUE, out_struct.dtype),)

        def cols_fn(cols):
            args = _cols_to_row(cols, schema)
            return {VALUE: jax.vmap(f)(args)}

    else:
        raise _NotTraceable(f"unsupported row fn output: {out_struct}")
    return out_schema, cols_fn


def _cols_to_row(cols, schema):
    names = [n for n, _ in schema]
    if set(names) == {KEY, VALUE}:
        return (cols[KEY], cols[VALUE])
    if names == [VALUE]:
        return cols[VALUE]
    return tuple(cols[n] for n in names)


# ---------------------------------------------------------------------------
# narrow nodes (fused at materialization)
# ---------------------------------------------------------------------------


class _NarrowRDD(DenseRDD):
    """A narrow dense op: shard-local (cols, count) -> (cols, count).
    Chains of narrow nodes compose into one jitted program."""

    # Nodes that override _materialize (capacity-changing expansions) are
    # chain BREAKS: a downstream narrow chain must materialize them via
    # their own program, never call their _shard_fn.
    _chainable = True

    def __init__(self, parent: DenseRDD, out_schema):
        super().__init__(parent.context, parent.mesh, [parent])
        self.parent = parent
        self._out_schema = tuple(out_schema)

    def _schema(self):
        return self._out_schema

    def _shard_fn(self, cols, count):
        raise NotImplementedError

    def _node_fp(self):
        """Program-cache identity of this node (kind + closure fingerprint)."""
        return (type(self).__name__, _fp(getattr(self, "_user_fn", None)))

    def _fp_extra(self):
        return self._node_fp()

    def _materialize(self) -> Block:
        # Collect the narrow chain down to the nearest materialization
        # root via the shared walk (exchange fusion uses the same one, so
        # the two sites cannot disagree about what a chain is).
        chain, root = _narrow_chain(self)
        chain = _detached_chain(chain)  # cached program must not pin nodes
        return _run_narrow_chain(self.mesh, chain, root.block(),
                                 self._out_schema)


def _run_narrow_chain(mesh, chain, root_block: Block, out_schema) -> Block:
    """Compile+launch ONE shard program applying a (detached) narrow
    chain over a materialized root block — the shared materializer behind
    _NarrowRDD._materialize and the frame A/B's chain-broken unfused
    nodes (one program-cache key scheme, one Block contract)."""
    names = list(root_block.cols)
    out_names = [n for n, _ in out_schema]
    cap = root_block.capacity

    def fused(counts, *col_arrays):
        cols = dict(zip(names, col_arrays))
        cols, count = _apply_chain(chain, cols, counts[0])
        return (count.reshape(1),) + tuple(cols[n] for n in out_names)

    key = ("narrow", mesh, tuple(names), tuple(out_names),
           _chain_fp(chain))
    prog = _cached_program(
        key,
        lambda: _shard_program(
            mesh, fused, 1 + len(names),
            (_SPEC,) * (1 + len(out_names)),
        ),
    )
    out = prog(root_block.counts, *[root_block.cols[n] for n in names])
    return Block(
        cols=dict(zip(out_names, out[1:])),
        counts=out[0], capacity=cap, mesh=mesh,
    )


class _MapRDD(_NarrowRDD):
    def __init__(self, parent: DenseRDD, f):
        out_schema, cols_fn = _trace_row_fn(f, parent._schema())
        super().__init__(parent, out_schema)
        self._cols_fn = cols_fn
        self._user_fn = f
        # A traced closure mints its outputs fresh — no dictionary rides
        # through by default. Trusted internal callers that merely MOVE a
        # dict column (distinct/set ops/count_by_value) overwrite this
        # right after construction.
        self._dict_renames = {}

    def _shard_fn(self, cols, count):
        return self._cols_fn(cols), count


class _MapValuesRDD(_NarrowRDD):
    def __init__(self, parent: DenseRDD, f):
        pschema = dict(parent._schema())
        # The single value column, whatever its name (canonical 'v' or a
        # named column from dense_from_columns).
        self._vname = next(nm for nm in pschema if nm not in (KEY, KEY_LO))
        try:
            out = jax.eval_shape(
                f, jax.ShapeDtypeStruct((), pschema[self._vname])
            )
        except Exception as e:  # noqa: BLE001
            raise _NotTraceable(str(e)) from e
        if not hasattr(out, "shape") or out.shape != ():
            raise _NotTraceable("map_values fn must return a scalar")
        key_schema = ((KEY, pschema[KEY]),)
        if KEY_LO in pschema:
            key_schema += ((KEY_LO, pschema[KEY_LO]),)
        super().__init__(parent, key_schema + ((self._vname, out.dtype),))
        self._f = f
        self._user_fn = f
        # Keys pass through untouched (dict KEY keeps its dictionary);
        # the value column is minted by the closure.
        self._dict_renames = {KEY: KEY}

    def _shard_fn(self, cols, count):
        out = {KEY: cols[KEY],
               self._vname: jax.vmap(self._f)(cols[self._vname])}
        if KEY_LO in cols:
            out[KEY_LO] = cols[KEY_LO]
        return out, count

    @property
    def hash_placed(self) -> bool:
        return self.parent.hash_placed  # keys untouched

    @property
    def key_sorted(self) -> bool:
        return self.parent.key_sorted  # order untouched

    def _settle_placement(self) -> None:
        self.parent._settle_placement()


class _FilterRDD(_NarrowRDD):
    def __init__(self, parent: DenseRDD, pred):
        schema = parent._schema()
        in_struct = _row_struct(schema)
        try:
            out = jax.eval_shape(pred, in_struct)
        except Exception as e:  # noqa: BLE001
            raise _NotTraceable(str(e)) from e
        if not hasattr(out, "shape") or out.shape != ():
            raise _NotTraceable("predicate must return a scalar bool")
        super().__init__(parent, schema)
        self._pred = pred
        self._user_fn = pred

    def _shard_fn(self, cols, count):
        cap = next(iter(cols.values())).shape[0]
        keep = jax.vmap(self._pred)(_cols_to_row(cols, self._out_schema))
        keep = keep.astype(jnp.bool_) & kernels.valid_mask(cap, count)
        return kernels.compact(cols, keep, cap)

    @property
    def hash_placed(self) -> bool:
        return self.parent.hash_placed  # surviving rows keep their keys

    @property
    def key_sorted(self) -> bool:
        return self.parent.key_sorted  # compact is stable

    def _settle_placement(self) -> None:
        self.parent._settle_placement()


def _fixed_payload_schema(payload, width: int, what: str):
    """Schema for a (width,)-array payload — one array (values) or a
    (keys, values) pair. Shared by map_expand and flat_map_ragged."""
    if isinstance(payload, tuple) and len(payload) == 2:
        if any(getattr(s, "shape", None) != (width,) for s in payload):
            raise _NotTraceable(
                f"{what} fn must return shape ({width},) arrays"
            )
        return ((KEY, payload[0].dtype), (VALUE, payload[1].dtype))
    if hasattr(payload, "shape"):
        if payload.shape != (width,):
            raise _NotTraceable(
                f"{what} fn must return a ({width},) array"
            )
        return ((VALUE, payload.dtype),)
    raise _NotTraceable(f"unsupported {what} output: {payload}")


class _MapExpandRDD(_NarrowRDD):
    """Fixed-factor row expansion: vmapped f gives [n, factor] outputs which
    interleave into factor*capacity rows, compacted to valid prefix."""

    _chainable = False  # overrides _materialize (capacity changes)

    def __init__(self, parent: DenseRDD, f, factor: int):
        if factor <= 0:
            raise VegaError("map_expand factor must be positive")
        in_struct = _row_struct(parent._schema())
        try:
            out = jax.eval_shape(f, in_struct)
        except Exception as e:  # noqa: BLE001
            raise _NotTraceable(str(e)) from e
        schema = _fixed_payload_schema(out, factor, "map_expand")
        super().__init__(parent, schema)
        self._f = f
        self._factor = factor
        self._user_fn = (f, factor)
        self._dict_renames = {}  # closure-minted outputs: no dict rides

    def _materialize(self) -> Block:
        # Expansion changes capacity; run as its own program (not chained).
        parent_blk = self.parent.block()
        names_in = list(parent_blk.cols)
        out_names = [n for n, _ in self._out_schema]
        factor = self._factor
        cap_in = parent_blk.capacity
        cap_out = block_lib._round_capacity(cap_in * factor)
        f = self._f
        in_schema = self.parent._schema()

        def prog_fn(counts, *col_arrays):
            cols = dict(zip(names_in, col_arrays))
            count = counts[0]
            args = _cols_to_row(cols, in_schema)
            out = jax.vmap(f)(args)  # leaves [cap_in, factor]
            if not isinstance(out, tuple):
                out = (out,)
            flat = {
                name: jnp.pad(o.reshape(-1), (0, cap_out - cap_in * factor))
                for name, o in zip(out_names, out)
            }
            idx = lax.iota(jnp.int32, cap_out)
            keep = idx < count * factor
            res, new_count = kernels.compact(flat, keep, cap_out)
            return (new_count.reshape(1),) + tuple(res[n] for n in out_names)

        key = ("map_expand", self.mesh, _fp(self._user_fn), cap_in, factor)
        prog = _cached_program(
            key,
            lambda: _shard_program(
                self.mesh, prog_fn, 1 + len(names_in),
                (_SPEC,) * (1 + len(out_names)),
            ),
        )
        outs = prog(parent_blk.counts,
                    *[parent_blk.cols[n] for n in names_in])
        return Block(cols=dict(zip(out_names, outs[1:])), counts=outs[0],
                     capacity=cap_out, mesh=self.mesh)

    def _shard_fn(self, cols, count):  # not chained; materialize overrides
        raise NotImplementedError


class _FlatMapRaggedRDD(_NarrowRDD):
    """Variable-arity flat_map on device: f(row) -> (out, n_valid) where
    out is one (max_out,) array (values) or a pair of (max_out,) arrays
    (key, value) and n_valid is how many lead entries are real.

    The XLA-compatible general flat_map (reference rdd.rs:207-214 is fully
    dynamic): per-row counts -> exclusive prefix sums -> each output slot
    finds its owning row by binary search (same ragged-expansion pattern as
    merge_join_expand). Output capacity is the static bound
    capacity * max_out, so no overflow is possible."""

    _chainable = False  # overrides _materialize (capacity changes)

    def __init__(self, parent: DenseRDD, f, max_out: int):
        if max_out <= 0:
            raise VegaError("flat_map_ragged max_out_per_row must be > 0")
        in_struct = _row_struct(parent._schema())
        try:
            out = jax.eval_shape(f, in_struct)
        except Exception as e:  # noqa: BLE001
            raise _NotTraceable(str(e)) from e
        if not (isinstance(out, tuple) and len(out) == 2):
            raise _NotTraceable(
                "flat_map_ragged fn must return (out_arrays, n_valid)"
            )
        payload, n_struct = out
        if getattr(n_struct, "shape", None) != ():
            raise _NotTraceable("n_valid must be a scalar")
        schema = _fixed_payload_schema(payload, max_out, "flat_map_ragged")
        super().__init__(parent, schema)
        self._f = f
        self._max_out = max_out
        self._user_fn = (f, max_out)
        self._dict_renames = {}  # closure-minted outputs: no dict rides

    def _materialize(self) -> Block:
        parent_blk = self.parent.block()
        names_in = list(parent_blk.cols)
        out_names = [n for n, _ in self._out_schema]
        max_out = self._max_out
        cap_in = parent_blk.capacity
        cap_out = block_lib._round_capacity(cap_in * max_out)
        f = self._f
        in_schema = self.parent._schema()

        def prog_fn(counts, *col_arrays):
            cols = dict(zip(names_in, col_arrays))
            count = counts[0]
            args = _cols_to_row(cols, in_schema)
            payload, n = jax.vmap(f)(args)  # leaves [cap_in, max_out]
            if not isinstance(payload, tuple):
                payload = (payload,)
            mask = kernels.valid_mask(cap_in, count)
            n = jnp.where(mask, jnp.clip(n.astype(jnp.int32), 0, max_out), 0)
            li, off, total = kernels.ragged_expand(n, cap_out)
            off = jnp.clip(off, 0, max_out - 1)
            res = {
                name: leaf[li, off]
                for name, leaf in zip(out_names, payload)
            }
            return (total.reshape(1),) + tuple(res[n_] for n_ in out_names)

        key = ("flat_map_ragged", self.mesh, _fp(self._user_fn), cap_in,
               max_out)
        prog = _cached_program(
            key,
            lambda: _shard_program(
                self.mesh, prog_fn, 1 + len(names_in),
                (_SPEC,) * (1 + len(out_names)),
            ),
        )
        outs = prog(parent_blk.counts,
                    *[parent_blk.cols[n] for n in names_in])
        return Block(cols=dict(zip(out_names, outs[1:])), counts=outs[0],
                     capacity=cap_out, mesh=self.mesh)

    def _shard_fn(self, cols, count):  # not chained; materialize overrides
        raise NotImplementedError


class _ZipWithIndexRDD(DenseRDD):
    def __init__(self, parent: DenseRDD):
        super().__init__(parent.context, parent.mesh, [parent])
        self.parent = parent
        # The value moves to the key slot unchanged; the index is fresh.
        self._dict_renames = {KEY: VALUE}

    def _schema(self):
        pschema = dict(self.parent._schema())
        return ((KEY, pschema[VALUE]), (VALUE, jnp.int32))

    def _materialize(self) -> Block:
        blk = self.parent.block()
        counts_host = blk.counts_np
        offsets = np.concatenate(
            [[0], np.cumsum(counts_host)[:-1]]
        ).astype(np.int32)
        offsets_dev = mesh_lib.host_put(offsets,
                                        mesh_lib.shard_spec(self.mesh))

        def prog_fn(offsets, counts, vals):
            shard_off = offsets[0]
            positions = shard_off + lax.iota(jnp.int32, vals.shape[0])
            return counts.reshape(1), vals, positions

        prog = _cached_program(
            ("zip_index", self.mesh, blk.capacity),
            lambda: _shard_program(self.mesh, prog_fn, 3, (_SPEC,) * 3),
        )
        counts, vals, pos = prog(offsets_dev, blk.counts, blk.cols[VALUE])
        return Block(cols={KEY: vals, VALUE: pos}, counts=counts,
                     capacity=blk.capacity, mesh=self.mesh,
                     counts_host=counts_host)


class _DenseZipRDD(DenseRDD):
    """Pairwise zip of co-indexed shards: (left value, right value). Shard
    counts must match (host semantics raise otherwise,
    reference: zip_rdd.rs:119-150)."""

    def __init__(self, left: DenseRDD, right: DenseRDD):
        super().__init__(left.context, left.mesh, [left, right])
        self.left = left
        self.right = right

    def _schema(self):
        l = dict(self.left._schema())
        r = dict(self.right._schema())
        return ((KEY, l[VALUE]), (VALUE, r[VALUE]))

    def _dicts(self):
        # Sides keep their OWN dictionaries (no cross-side comparison
        # happens in a zip): left value -> KEY, right value -> VALUE.
        out = {}
        ld = self.left._dicts().get(VALUE)
        rd = self.right._dicts().get(VALUE)
        if ld is not None:
            out[KEY] = ld
        if rd is not None:
            out[VALUE] = rd
        return out

    def _materialize(self) -> Block:
        lb = self.left.block()
        rb = self.right.block()
        lc = lb.counts_np
        rc = rb.counts_np
        if not np.array_equal(lc, rc):
            raise VegaError(
                "dense zip requires equal per-shard counts; repartition or "
                "use .to_rdd().zip(...)"
            )
        cap = max(lb.capacity, rb.capacity)

        def prog_fn(counts, lv, rv):
            pad_l = cap - lv.shape[0]
            pad_r = cap - rv.shape[0]
            return (counts.reshape(1),
                    jnp.pad(lv, (0, pad_l)), jnp.pad(rv, (0, pad_r)))

        prog = _cached_program(
            ("dense_zip", self.mesh, lb.capacity, rb.capacity),
            lambda: _shard_program(self.mesh, prog_fn, 3, (_SPEC,) * 3),
        )
        counts, lv, rv = prog(lb.counts, lb.cols[VALUE], rb.cols[VALUE])
        return Block(cols={KEY: lv, VALUE: rv}, counts=counts, capacity=cap,
                     mesh=self.mesh, counts_host=lc)


class _SelectRDD(_NarrowRDD):
    def __init__(self, parent: DenseRDD, names):
        pschema = dict(parent._schema())
        super().__init__(parent, tuple((n, pschema[n]) for n in names))
        self._names = tuple(names)
        self._user_fn = self._names

    def _shard_fn(self, cols, count):
        return {n: cols[n] for n in self._names}, count

    @property
    def hash_placed(self) -> bool:
        return KEY in self._names and self.parent.hash_placed

    @property
    def key_sorted(self) -> bool:
        return KEY in self._names and self.parent.key_sorted

    def _settle_placement(self) -> None:
        self.parent._settle_placement()


class _RenameRDD(_NarrowRDD):
    """Value-column rename (keys untouched, so placement/order survive)."""

    def __init__(self, parent: DenseRDD, mapping: dict):
        pschema = parent._schema()
        super().__init__(parent, tuple(
            (mapping.get(nm, nm), dt) for nm, dt in pschema))
        self._mapping = dict(mapping)
        self._user_fn = tuple(sorted(mapping.items()))
        # Dictionaries follow their columns to the new names (identity
        # for unrenamed columns).
        self._dict_renames = {mapping.get(nm, nm): nm for nm, _ in pschema}

    def _shard_fn(self, cols, count):
        return {self._mapping.get(nm, nm): col
                for nm, col in cols.items()}, count

    @property
    def hash_placed(self) -> bool:
        return self.parent.hash_placed

    @property
    def key_sorted(self) -> bool:
        return self.parent.key_sorted

    def _settle_placement(self) -> None:
        self.parent._settle_placement()


class _OnesValueRDD(_NarrowRDD):
    """Key columns + a synthesized int32 ones VALUE column —
    count_by_key_dense's map side (counting needs no value bytes, so any
    existing value columns are dropped before the exchange moves data;
    the canonical VALUE name keeps the (k, count) host row form)."""

    def __init__(self, parent: DenseRDD):
        pschema = dict(parent._schema())
        out = [(nm, pschema[nm]) for nm in (KEY, KEY_LO) if nm in pschema]
        out.append((VALUE, jnp.int32))
        super().__init__(parent, tuple(out))
        self._user_fn = "ones_value"
        # KEY passes through (keeps its dictionary); VALUE is fresh ones.
        self._dict_renames = {KEY: KEY}

    def _shard_fn(self, cols, count):
        out = {nm: cols[nm] for nm in cols if nm in (KEY, KEY_LO)}
        out[VALUE] = jnp.ones_like(cols[KEY], dtype=jnp.int32)
        return out, count

    @property
    def hash_placed(self) -> bool:
        return self.parent.hash_placed

    @property
    def key_sorted(self) -> bool:
        return self.parent.key_sorted

    def _settle_placement(self) -> None:
        self.parent._settle_placement()


class _WidenKeyRDD(_NarrowRDD):
    """Re-encode an int32 KEY as the (hi, lo) two-column int64 encoding so
    the side can join/cogroup an int64-keyed block (same logical keys ->
    same bucket under the composite hash). hash_placed intentionally resets
    (default False): placement under the single-key hash says nothing
    about placement under the composite hash."""

    def __init__(self, parent: DenseRDD):
        out = []
        for nm, dt in parent._schema():
            if nm == KEY:
                out.append((KEY, jnp.int32))
                out.append((KEY_LO, jnp.int32))
            else:
                out.append((nm, dt))
        super().__init__(parent, tuple(out))
        self._user_fn = "widen_key"

    def _shard_fn(self, cols, count):
        k = cols[KEY]
        # hi = sign word (== int64(k) >> 32); lo = bits of k with the sign
        # bit flipped (signed compare == unsigned compare of true low word)
        # — identical to block.encode_i64 on the host.
        hi = k >> jnp.int32(31)
        lo = lax.bitcast_convert_type(
            lax.bitcast_convert_type(k, jnp.uint32) ^ jnp.uint32(0x80000000),
            jnp.int32,
        )
        out = {KEY: hi, KEY_LO: lo}
        for nm, c in cols.items():
            if nm != KEY:
                out[nm] = c
        return out, count


def _align_keys(a: DenseRDD, b: DenseRDD):
    """Make two dense pair sides key-compatible for device matching
    (join/cogroup): equal logical keys must hash to the same shard and
    compare equal in the merge kernel. Returns the (possibly widened)
    sides, or None when only the host tier can match them faithfully
    (mismatched key dtypes — e.g. int32 2 vs float32 2.0 hash apart on
    device but compare equal under Python semantics)."""
    pair = _unify_dict_cols(a, b, (KEY,))
    if pair is None:
        # One side's KEY is dictionary-encoded strings, the other's is
        # plain ints: a code 2 and an int 2 would match on device but
        # differ on the host — only the host tier matches faithfully.
        return None
    a, b = pair
    sa, sb = dict(a._schema()), dict(b._schema())
    wide_a, wide_b = KEY_LO in sa, KEY_LO in sb
    if wide_a == wide_b:
        if jnp.dtype(sa[KEY]) == jnp.dtype(sb[KEY]):
            return a, b
        return None
    narrow = b if wide_a else a
    if jnp.dtype(dict(narrow._schema())[KEY]) != jnp.dtype(jnp.int32):
        return None
    widened = _WidenKeyRDD(narrow)
    return (a, widened) if wide_a else (widened, b)


class _DictUnification:
    """Shared host-side dictionary merge for one binary op: both sides'
    _DictUnifyRDD wrappers reference ONE instance, so the merge runs once
    and the sides agree bit-identically on the unified code space. The
    merge itself (np.union1d + searchsorted remap tables,
    dict_encoding.merge_dicts) is lazy — graph construction stays cheap
    until a wrapper actually needs the tables."""

    def __init__(self, left_dicts, right_dicts, names):
        self.names = tuple(names)
        self._left = {nm: left_dicts[nm] for nm in self.names}
        self._right = {nm: right_dicts[nm] for nm in self.names}
        self._memo = None

    def tables(self):
        """(merged, left_maps, right_maps): per-name merged sorted
        dictionary plus int32 remap tables (old code -> merged code)."""
        if self._memo is None:
            from vega_tpu.tpu import dict_encoding

            merged, lmaps, rmaps = {}, {}, {}
            for nm in self.names:
                m, lt, rt = dict_encoding.merge_dicts(
                    self._left[nm], self._right[nm])
                merged[nm], lmaps[nm], rmaps[nm] = m, lt, rt
            self._memo = (merged, lmaps, rmaps)
        return self._memo

    def token(self):
        """Cheap picklable identity for fingerprints — input dictionary
        shapes and endpoints, no forced merge. Collisions only alias
        capacity HINTS (the overflow retry is the safety net, as ever)."""
        out = []
        for nm in self.names:
            for d in (self._left[nm], self._right[nm]):
                out.append((nm, len(d),
                            str(d[0]) if len(d) else "",
                            str(d[-1]) if len(d) else ""))
        return tuple(out)


class _DictUnifyRDD(_NarrowRDD):
    """Remap one side's dictionary codes onto the shared merged
    dictionary: ONE device gather through a staged remap table per
    unified column. The staged table capacity is a REAL capacity
    (Configuration.dense_dict_capacity): a valid code at or past the
    staged prefix sets the device overflow flag — checked on the RAW
    codes, like the dense-key table plan — and the driver retries with
    the capacity doubled. Monotonic remap (sorted dicts in, sorted merge
    out), so per-shard key order survives; hash placement does NOT (the
    codes hashed into buckets changed), hence the default hash_placed
    False."""

    _chainable = False  # own program (replicated table operands)

    def __init__(self, parent: DenseRDD, unif: _DictUnification, side: int):
        super().__init__(parent, parent._schema())
        self._unif = unif
        self._side = side
        self._dict_retries = 0  # overflow->grown-capacity rounds (tests)
        self._user_fn = ("dict_unify", side, unif.token())

    def _dicts(self):
        merged = self._unif.tables()[0]
        out = dict(self.parent._dicts())
        for nm in self._unif.names:
            if nm in out:
                out[nm] = merged[nm]
        return out

    @property
    def key_sorted(self) -> bool:
        return self.parent.key_sorted  # monotonic remap keeps order

    def _settle_placement(self) -> None:
        self.parent._settle_placement()

    def _materialize(self) -> Block:
        from vega_tpu.tpu import dict_encoding

        blk = self.parent.block()
        _, lmaps, rmaps = self._unif.tables()
        side_tables = lmaps if self._side == 0 else rmaps
        names = [nm for nm in self._unif.names if nm in blk.cols]
        if not names:
            return blk
        in_names = list(blk.cols)
        cap_tab = max(128, dict_encoding.dict_capacity())
        table_n = max(len(side_tables[nm]) for nm in names)
        for _round in range(8):
            staged_n = tuple(min(len(side_tables[nm]), cap_tab)
                             for nm in names)
            tabs = []
            for nm, sn in zip(names, staged_n):
                t = np.zeros(cap_tab, dtype=np.int32)
                t[:sn] = side_tables[nm][:sn]
                tabs.append(mesh_lib.host_put(
                    t, mesh_lib.replicated_spec(self.mesh)))
            n_tab = len(names)

            def prog_fn(*args):
                tables = dict(zip(names, args[:n_tab]))
                counts = args[n_tab]
                cols = dict(zip(in_names, args[n_tab + 1:]))
                count = counts[0]
                cap_rows = next(iter(cols.values())).shape[0]
                valid = kernels.valid_mask(cap_rows, count)
                flag = jnp.zeros((), jnp.int32)
                out = dict(cols)
                for nm, sn in zip(names, staged_n):
                    codes = cols[nm]
                    # Overflow checked on the RAW codes (never the
                    # clamped gather index): any valid code past the
                    # staged prefix means the table was truncated.
                    bad = valid & ((codes < 0)
                                   | (codes >= jnp.int32(sn)))
                    flag = flag | jnp.any(bad).astype(jnp.int32)
                    out[nm] = jnp.take(
                        tables[nm], jnp.clip(codes, 0, cap_tab - 1))
                return ((flag.reshape(1),)
                        + tuple(out[nm] for nm in in_names))

            prog = _cached_program(
                ("dict_remap", self.mesh, tuple(in_names), tuple(names),
                 cap_tab, staged_n, blk.capacity),
                lambda: _shard_program(
                    self.mesh, prog_fn,
                    tuple([_REPL] * n_tab) + (_SPEC,) * (1 + len(in_names)),
                    (_SPEC,) * (1 + len(in_names)),
                ),
            )
            outs = prog(*tabs, blk.counts,
                        *[blk.cols[nm] for nm in in_names])
            flag = np.asarray(mesh_lib.host_get(outs[0]))
            if not flag.any():
                return Block(
                    cols=dict(zip(in_names, outs[1:])),
                    counts=blk.counts, capacity=blk.capacity,
                    mesh=self.mesh, counts_host=blk.counts_host,
                )
            self._dict_retries += 1
            cap_tab *= 2
        raise VegaError(
            f"dictionary remap overflowed {table_n} entries after 8 "
            "capacity-doubling retries — raise dense_dict_capacity"
        )


def _unify_dict_cols(a: DenseRDD, b: DenseRDD, names):
    """Align the named dictionary-encoded columns of two sides onto one
    merged dictionary so device code equality == string equality.
    Returns the (possibly wrapped) sides; (a, b) unchanged when nothing
    needs remapping (no dict columns, or both sides already share the
    same dictionary arrays); None when dict-ness MISMATCHES on a name —
    codes on one side and plain values on the other only compare
    faithfully on the host tier."""
    da, db = a._dicts(), b._dicts()
    shared = [nm for nm in names if nm in da or nm in db]
    if not shared:
        return a, b
    if any((nm in da) != (nm in db) for nm in shared):
        return None
    todo = [nm for nm in shared if da[nm] is not db[nm]]
    if not todo:
        return a, b
    unif = _DictUnification(da, db, todo)
    return _DictUnifyRDD(a, unif, 0), _DictUnifyRDD(b, unif, 1)


class _ProjectRDD(_NarrowRDD):
    def __init__(self, parent: DenseRDD, col: str):
        pschema = dict(parent._schema())
        if col not in pschema:
            raise VegaError(
                f"no {col!r} column on this DenseRDD (columns: "
                f"{list(pschema)})"
            )
        super().__init__(parent, ((VALUE, pschema[col]),))
        self._col = col
        self._user_fn = col
        # The projected column keeps its dictionary under the VALUE name.
        self._dict_renames = {VALUE: col}

    def _shard_fn(self, cols, count):
        return {VALUE: cols[self._col]}, count


class _ColsPipelineRDD(_NarrowRDD):
    """Multi-op traced closure entry: ONE narrow node applying an arbitrary
    columnwise (cols, count) -> (cols, count) pipeline with a declared
    output schema and a stable fingerprint token. The frame planner
    (vega_tpu/frame) lowers a whole select/filter/with_column stage onto a
    single instance, so the stage compiles to exactly one shard program —
    and still rides the existing chain fusion when stacked on other narrow
    nodes. `fused=False` breaks the chain: the node materializes through
    its OWN single-step program (the frame A/B's unfused leg)."""

    def __init__(self, parent: DenseRDD, cols_fn, out_schema, token,
                 fused: bool = True, dict_renames=None):
        super().__init__(parent, out_schema)
        self._cols_fn = cols_fn
        self._user_fn = token  # _node_fp pickles this, not the closure
        # The planner DECLARES which output columns are pass-throughs of
        # dictionary-encoded parent columns ({out name -> parent name});
        # everything else is closure-minted and drops its dictionary.
        self._dict_renames = dict(dict_renames or {})
        if not fused:
            self._chainable = False

    def _shard_fn(self, cols, count):
        return self._cols_fn(cols, count)

    def _materialize(self) -> Block:
        if self._chainable:
            return _NarrowRDD._materialize(self)
        # Unfused: a one-node chain over the materialized parent — its own
        # program launch and its own intermediate block, deliberately (the
        # fusion A/B's control leg must pay per-op launches).
        return _run_narrow_chain(self.mesh, _detached_chain([self]),
                                 self.parent.block(), self._out_schema)


def dense_pipeline(parent: DenseRDD, cols_fn, out_schema, token,
                   fused: bool = True, dict_renames=None) -> DenseRDD:
    """Public factory for _ColsPipelineRDD (the frame planner's whole-stage
    entry). `out_schema` is ((name, dtype), ...); `token` must be a stable
    picklable description of the pipeline (it keys the program cache);
    `dict_renames` maps output columns that pass a dictionary-encoded
    parent column through unchanged to that parent column's name."""
    return _ColsPipelineRDD(parent, cols_fn, out_schema, token, fused=fused,
                            dict_renames=dict_renames)


# ---------------------------------------------------------------------------
# source nodes
# ---------------------------------------------------------------------------


class _HostDenseView(RDD):
    """What an unpickled DenseRDD is: the materialized rows as host numpy,
    original shard structure preserved, iteration-only surface (compute /
    iterator / collect). Device ops are not available — a shipped dense
    node is consumed by host-tier tasks, never re-launched as SPMD."""

    def __init__(self, *a, **kw):  # pragma: no cover — pickle-only
        raise TypeError("_HostDenseView is created by unpickling a DenseRDD")

    @property
    def num_partitions(self) -> int:
        return self._host_block.n_shards

    def block(self) -> Block:
        return self._host_block

    def __getstate__(self):
        return self.__dict__.copy()

    def __setstate__(self, state):
        self.__dict__.update(state)

    def compute(self, split: Split, task_context=None):
        yield from _yield_rows(self._host_block.shard_rows(split.index))


class _SourceRDD(DenseRDD):
    def __init__(self, ctx, blk: Block, hash_placed: bool = False):
        super().__init__(ctx, blk.mesh)
        self._block = blk
        self._hash_placed = hash_placed

    @property
    def hash_placed(self) -> bool:
        return self._hash_placed

    def _materialize(self) -> Block:
        return self._block

    def unpersist(self) -> "DenseRDD":
        """No-op: a source's Block IS its data — there is no lineage to
        rebuild it from, so releasing it would lose the dataset. Source
        footprint is gated at creation (the streaming planner caps
        whole-block sources at dense_hbm_budget)."""
        return self

    def _schema(self):
        return tuple((n, c.dtype) for n, c in self._block.cols.items())

    def _dicts(self):
        return dict(self._block.dicts or {})

    def _fp_extra(self):
        return (tuple((n, str(c.dtype)) for n, c in self._block.cols.items()),
                self._block.capacity, self._hash_placed)


def dense_range(ctx, n: int, num_partitions=None, dtype=None,
                chunk_rows: Optional[int] = None):
    """Device iota source. When the estimated exchange footprint over the
    whole block (the exchange planner's peak estimate under
    dense_exchange=auto; ~6x block bytes otherwise) exceeds
    Configuration.dense_hbm_budget, returns a StreamedDenseRDD that flows
    chunk by chunk through the mesh instead of materializing whole (the
    1B-row single-chip path); pass chunk_rows to force streaming."""
    from vega_tpu.env import Env
    from vega_tpu.tpu.stream import planned_chunk_rows, streamed_range

    mesh = mesh_lib.default_mesh()
    dtype = dtype or jnp.int32
    rows = planned_chunk_rows(
        n, jnp.dtype(dtype).itemsize,
        getattr(Env.get().conf, "dense_hbm_budget", 4 << 30),
        chunk_rows, n_shards=mesh.size,
    )
    if rows is not None and rows < n:
        return streamed_range(ctx, n, rows, mesh, dtype)
    return _SourceRDD(ctx, block_lib.block_range(n, mesh, dtype))


def dense_from_numpy(ctx, columns, num_partitions=None):
    """columns: one array (values) or two arrays (keys, values).

    Data the device tier cannot represent faithfully (int64 beyond int32
    range without jax x64 — keys would silently collide) degrades to the
    HOST tier, never errors: the two-tier contract applied to dtypes. The
    host tier keeps exact int64 semantics."""
    mesh = mesh_lib.default_mesh()
    try:
        if len(columns) == 1:
            blk = block_lib.single_column(columns[0], mesh)
        elif len(columns) == 2:
            blk = block_lib.pair_block(columns[0], columns[1], mesh)
        else:
            named = {f"c{i}": np.asarray(c) for i, c in enumerate(columns)}
            blk = block_lib.from_numpy(named, mesh)
    except VegaError as e:
        log.info("dense_from_numpy fell back to host tier: %s", e)
        arrays = [np.asarray(c) for c in columns]
        if len(arrays) == 1:
            data = arrays[0].tolist()
        elif len(arrays) == 2:
            data = list(zip(arrays[0].tolist(), arrays[1].tolist()))
        else:
            data = list(zip(*[a.tolist() for a in arrays]))
        return ctx.parallelize(data, num_partitions)
    return _SourceRDD(ctx, blk)


def dense_from_columns(ctx, columns: Optional[dict] = None,
                       key: Optional[str] = None, **kwcolumns) -> DenseRDD:
    """Named-column dense source (the columnar-analytics face of the tier):
    any number of value columns; `key=` names the column used as the shuffle
    key. reduce_by_key with a named op reduces EVERY value column per key in
    one program (kernels.segment_reduce_named is generic over columns) —
    e.g. a parquet table flows in with zero pivoting:

        blk = pq.read_table(p).to_pydict()
        rdd = ctx.dense_from_columns(blk, key="ip")
        per_ip = rdd.reduce_by_key(op="add")     # sums every other column

    Columns may come as a dict (works for any column names, including
    "key") and/or keywords.
    """
    named = {}
    for source in (columns or {}), kwcolumns:
        for name, col in source.items():
            if name in named:
                raise VegaError(f"duplicate column {name!r}")
            if block_lib.is_lo(name):
                # The ".lo" suffix is reserved for the low word of wide
                # (two-column int64) encodings: a user column with such a
                # name would be silently consumed as low-word bits (wrong
                # int64 values, vanished data).
                raise VegaError(
                    f"column name {name!r} is reserved (the "
                    f"{block_lib.LO_SUFFIX!r} suffix marks low words of "
                    "two-column int64 encodings) — rename the column"
                )
            named[name] = np.asarray(col)
    lengths = {name: len(col) for name, col in named.items()}
    if len(set(lengths.values())) > 1:
        raise VegaError(f"columns have unequal lengths: {lengths}")
    if key is not None:
        if key not in named:
            raise VegaError(f"key column {key!r} not in columns")
        if KEY in named and key != KEY:
            raise VegaError(
                f"column {KEY!r} already exists; key={key!r} would "
                f"overwrite it — rename one of them"
            )
        named[KEY] = named.pop(key)
    try:
        blk = block_lib.from_numpy(named, mesh_lib.default_mesh())
    except VegaError as e:
        if set(named) == {KEY, VALUE}:
            # Same dtype degrade as dense_from_numpy: the canonical pair
            # layout has a host row form, so fall back instead of erroring.
            log.info("dense_from_columns fell back to host tier: %s", e)
            return ctx.parallelize(
                list(zip(np.asarray(named[KEY]).tolist(),
                         np.asarray(named[VALUE]).tolist()))
            )
        raise  # named/multi-column blocks: documented crisp-error exception
    return _SourceRDD(ctx, blk)


def dense_from_block(ctx, blk: Block, hash_placed: bool = False) -> DenseRDD:
    return _SourceRDD(ctx, blk, hash_placed=hash_placed)


def dense_load_npz(ctx, path: str, chunk_rows: Optional[int] = None):
    """Load a block persisted with DenseRDD.save_npz; data is re-sharded
    over the current default mesh (so a block saved on one topology loads
    onto another — the persistence story the reference lacks entirely,
    SURVEY.md §5 'Checkpoint/resume: none'). Files bigger than the HBM
    budget stream chunk by chunk (host RAM holds the file; HBM holds one
    chunk); pass chunk_rows to force streaming."""
    from vega_tpu.env import Env
    from vega_tpu.tpu.stream import planned_chunk_rows, streamed_npz

    with np.load(path, allow_pickle=False) as data:
        cols = {n: data[n] for n in data.files}
    n = len(next(iter(cols.values()))) if cols else 0
    bytes_per_row = sum(
        c.dtype.itemsize * int(np.prod(c.shape[1:], dtype=np.int64))
        for c in cols.values()
    ) or 1
    rows = planned_chunk_rows(
        n, bytes_per_row,
        getattr(Env.get().conf, "dense_hbm_budget", 4 << 30),
        chunk_rows, n_shards=mesh_lib.default_mesh().size,
    )
    if rows is not None and rows < n:
        # Reuse the already-loaded host columns — no second npz read.
        return streamed_npz(ctx, cols, rows, mesh_lib.default_mesh())
    blk = block_lib.from_numpy(cols, mesh_lib.default_mesh())
    return _SourceRDD(ctx, blk)


# ---------------------------------------------------------------------------
# exchange nodes (device shuffles)
# ---------------------------------------------------------------------------


def _cap_round(c: int) -> int:
    """Shape-stable capacity rounding (pow2 under 1M, 1M-multiples above —
    see block._round_capacity)."""
    return block_lib._round_capacity(c)


def _exchange_capacities(counts: np.ndarray, n_shards: int,
                         attempt: int) -> Tuple[int, int]:
    """Heuristic slot/out capacities with growth on retry, rounded to
    shape-stable buckets so repeated pipelines at similar scale reuse
    compiled programs."""
    max_count = int(counts.max()) if counts.size else 1
    total = int(counts.sum())
    grow = 2 ** attempt
    slot = min(
        _cap_round(max_count),
        _cap_round((math.ceil(max_count / max(n_shards, 1)) * 2 + 64) * grow),
    )
    out = min(
        _cap_round(total),
        _cap_round((math.ceil(total / max(n_shards, 1)) * 2 + 64) * grow),
    )
    return slot, out


def _histogram_capacities(hists: List[np.ndarray], attempt: int,
                          slot_hists: Optional[List[np.ndarray]] = None
                          ) -> Tuple[int, int]:
    """Exact slot/out capacities from per-shard destination histograms.

    Each hist is [n_shards, n_shards]: hist[s, t] = rows shard s sends to
    target t. slot must hold the largest single (sender, target) cell; out
    must hold the largest per-target column sum. Sized from the real key
    distribution, overflow retries (which recompile a bigger program,
    multi-second jit stalls on TPU) become an anomaly instead of the
    expected path under skew. Growth on retry is kept as a safety net for
    exchanges whose histogram is an estimate (none today).

    slot_hists, when given, restricts the slot (send-buffer) sizing to
    those hists: elided (diagonal) sides never send, and letting their
    per-shard totals into the slot max would oversize the other side's
    [n_shards, slot] buffers."""
    grow = 2 ** attempt
    src = hists if slot_hists is None else slot_hists
    slot = max((int(h.max()) for h in src), default=1)
    out = max(int(h.sum(axis=0).max()) for h in hists)
    return _cap_round(max(slot, 1) * grow), _cap_round(max(out, 1) * grow)


def _with_exchange(node, exchange: Optional[str]):
    if exchange is not None:
        node.exchange_mode = exchange
    return node


# The elided / planner-bypassed token builds program-cache keys on paths
# that never launch a collective (passthrough or single-shard): the key
# slot stays populated so elided and planned programs of one lineage
# never collide.
_X_ELIDED = ("elided",)


def _lo_of(names) -> Optional[str]:
    """KEY_LO when the schema carries a two-column int64 key, else None —
    the switch every keyed device kernel takes."""
    return KEY_LO if KEY_LO in names else None


def _narrow_chain(node):
    """(chain, root) where chain is the longest not-yet-materialized
    chainable narrow run ending at `node` (possibly empty) and root is the
    nearest materialization point above it. Exchanges fuse the chain into
    their own program: the map/filter work runs inside the exchange launch
    (one launch instead of two, no intermediate block in HBM) — XLA-style
    rematerialization applied to the lineage. A chain parent that was
    already materialized (shared by another consumer) is used as-is."""
    chain: List[_NarrowRDD] = []
    cur = node
    while isinstance(cur, _NarrowRDD) and cur._block is None \
            and cur._chainable:
        chain.append(cur)
        cur = cur.parent
    chain.reverse()
    return chain, cur


def _apply_chain(chain, cols, count):
    for nd in chain:
        cols, count = nd._shard_fn(cols, count)
    return cols, count


def _chain_fp(chain) -> tuple:
    return tuple(nd._node_fp() for nd in chain)


def _sort_impl() -> str:
    """kernels.resolve_sort_impl — 'radix' routes the key sorts in the
    exchange programs through the LSD radix path (Pallas-streamed passes
    on TPU), 'packed' packs (key, perm) into one 63-bit word for XLA's
    fast single-operand sort, 'auto' resolves per backend from measured
    evidence (env.py dense_sort_impl note)."""
    return kernels.resolve_sort_impl()


def _bucket_cols(cols, n: int) -> jax.Array:
    """Hash-bucket rows by key, two-column int64 keys included. The
    composite hash mixes BOTH words (hash32_pair) so placement keeps its
    contract: equal int64 keys — and only those — share a bucket."""
    if KEY_LO in cols:
        return (kernels.hash32_pair(cols[KEY], cols[KEY_LO])
                % jnp.uint32(n)).astype(jnp.int32)
    return pallas_kernels.hash_bucket(cols[KEY], n)


def _elide_out_cap(blk: Block) -> int:
    """Output capacity for an elided (passthrough) exchange: rows stay
    put, so the parent's max shard count bounds it exactly when already
    host-known; otherwise the parent's static capacity (a safe superset,
    usually the same rounding bucket) — never worth a counts fetch."""
    if blk.counts_host is not None and blk.counts_host.size:
        return block_lib._round_capacity(max(int(blk.counts_host.max()), 1))
    return blk.capacity


def _settle_pending(ctx) -> None:
    """Verify every deferred (speculative) exchange in ONE device
    transfer; repair failures in place.

    A hinted/fixed-capacity exchange launches without its blocking
    (counts, overflow) fetch — on the wedge-prone tunnel each such fetch
    is a full network RTT between otherwise-pipelined launches — and
    registers here instead. The next genuine host read settles the whole
    backlog: one device_get over all pending flags, then per entry either
    commit (write counts_host, refresh the capacity hint) or, from the
    first failure onward, invalidate and re-materialize with deferral
    disabled (the normal histogram-sized blocking path) and copy the
    clean result INTO the old Block object so every captured reference
    observes the repair. Entries registered after a failure are rebuilt
    too: they were launched against the failed block's truncated data."""
    pend = ctx.__dict__.get("_dense_pending")
    if not pend:
        return
    entries = list(pend)
    pend.clear()  # repairs below re-enter _run_exchange -> _settle_pending
    hint_store = ctx.__dict__.setdefault("_dense_capacity_hints", {})

    def commit(e, head):
        blk = e["block"]
        blk.counts_host = head[0].reshape(-1)
        blk.settle = None
        if e["hint_key"] is not None:
            # pop-then-insert refreshes recency (front of the dict is
            # the eviction end, _run_exchange's bookkeeping).
            hint_store.pop(e["hint_key"], None)
            hint_store[e["hint_key"]] = e["caps"]
            while len(hint_store) > 4096:
                hint_store.pop(next(iter(hint_store)))
        if e["on_success"] is not None:
            e["on_success"](head)

    def depends_on(rdd, failed_rdds) -> bool:
        """True if rdd's dense lineage reaches any failed node (possibly
        through non-pending intermediates)."""
        seen = set()
        stack = [rdd]
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            if id(node) in failed_rdds:
                return True
            stack.extend(node._dense_parents)
        return False

    failed = []          # entries to invalidate + rebuild, in order
    failed_rdds = set()
    i = 0
    try:
        fetched = mesh_lib.host_get(
            [(e["outs_head"], e["overflow"]) for e in entries])
        for i, (e, (head, ovf)) in enumerate(zip(entries, fetched)):
            head = [np.asarray(h) for h in head]
            bad = failed_rdds and depends_on(e["rdd"], failed_rdds)
            if not bad:
                ok = not bool(np.any(np.asarray(ovf)))
                validator_said_no = False
                if ok and e["validate"] is not None:
                    # Join product checks; a hard limit raises VegaError.
                    ok = e["validate"](head)
                    validator_said_no = not ok
                if ok:
                    # Clean flags AND no failed ancestor: commit even
                    # after an unrelated pipeline's failure — only
                    # lineage descendants consumed truncated data.
                    commit(e, head)
                    continue
                # An exchange overflow means the hinted capacities were
                # wrong — drop the hint so the repair sizes from
                # histograms. A validator failure (join product exceeded
                # its cap) keeps the exchange hint: the validator already
                # stashed its corrected cap.
                if e["hint_key"] is not None and not validator_said_no:
                    hint_store.pop(e["hint_key"], None)
            failed.append(e)
            failed_rdds.add(id(e["rdd"]))
    except Exception:
        # Settlement died mid-way (validator hard error, transport
        # failure): every entry not yet committed goes BACK on the
        # backlog, in order — a stranded entry whose settle became a
        # no-op would silently serve capacity-truncated data later.
        # That includes entries already triaged into `failed` but not
        # yet repaired: re-processing them is idempotent (their
        # overflow flags re-fail and route back through repair).
        # (A deterministic validator error thus re-raises on every
        # subsequent read of the affected pipeline: loud, never wrong.)
        pend[:0] = failed + entries[i:]
        raise
    if not failed:
        return
    log.info("speculative exchange failed (%d of %d entries); repairing",
             len(failed), len(entries))
    for e in failed:
        e["rdd"]._block = None
        e["rdd"].__dict__.pop("_pickle_state_memo", None)
        # Until repaired, reads through captured references must fail
        # loudly, not fetch the truncated speculative buffers.
        e["block"].settle = _unrepaired_raise
    ctx.__dict__["_dense_no_defer"] = True
    try:
        for e in failed:
            rdd = e["rdd"]
            fresh = rdd.block()  # blocking path: sized, fetched, verified
            old = e["block"]
            old.cols = fresh.cols
            old.counts = fresh.counts
            old.capacity = fresh.capacity
            old.counts_host = fresh.counts_np
            old.settle = None
            old._host_cols_cache = None  # repaired cols: drop stale copy
            rdd._block = old  # keep the object identity callers captured
    finally:
        ctx.__dict__["_dense_no_defer"] = False


def _unrepaired_raise():
    raise VegaError(
        "speculative block was invalidated by an exchange overflow and "
        "its repair did not complete; re-run the pipeline"
    )


class _ExchangeRDD(DenseRDD):
    """Common driver loop: run the fused exchange program, check overflow
    flags, retry with grown capacities (capacity-factor pattern). The
    collective implementation (one-shot all_to_all, staged K-round, or
    ring) is resolved per launch by the cost model in
    tpu/exchange_plan.py under Configuration.dense_exchange="auto", or
    forced by an explicit mode / the node's exchange_mode attribute."""

    # Last resolved plan; stays None on single-shard meshes (the
    # passthrough plans nothing) so readers must null-check.
    _exchange_plan = None

    def _attach_pending(self, blk: Block) -> Block:
        """Register the deferred entry _run_exchange left behind (if any)
        against the just-built Block; returns blk either way."""
        entry = self.__dict__.pop("_deferred_entry", None)
        if entry is None:
            return blk
        entry["block"] = blk
        ctx = self.context
        ctx.__dict__.setdefault("_dense_pending", []).append(entry)
        blk.settle = lambda: _settle_pending(ctx)
        return blk

    @property
    def exchange_mode(self) -> str:
        mode = getattr(self, "_exchange_mode", None)
        if mode is None:
            from vega_tpu.env import Env

            mode = getattr(Env.get().conf, "dense_exchange", "auto")
        return mode

    @exchange_mode.setter
    def exchange_mode(self, mode: str) -> None:
        self._exchange_mode = mode

    def _resolve_exchange(self, blks, slot_capacity: int,
                          out_capacity: int):
        """Resolve the exchange implementation for ONE launch through the
        collective-aware planner (tpu/exchange_plan.py): explicit modes
        map straight to their program; "auto" picks the fewest-rounds
        program whose estimated per-shard peak fits dense_hbm_budget
        (all_to_all -> staged -> ring). Returns (exchange_callable,
        plan_token); the token goes into the program-cache key — the
        budget is config, not key, so the RESOLVED choice must be.

        Called from inside build(slot, out_cap): capacities are only
        known per launch (hints, histograms, growth retries), and a
        retry's grown slot may legitimately shift the plan. `blks` are
        the operand blocks actually exchanged — a join passes both
        non-elided sides, and the estimate models the JOINT launch
        footprint (both operands and outputs live together, the
        costlier side's transients on top), not the max of the sides.
        Records the plan on the node (_exchange_plan), the module
        counters, and the event bus (DenseExchangePlanned ->
        MetricsListener) for observability."""
        from vega_tpu.env import Env
        from vega_tpu.tpu import exchange_plan

        n = self.mesh.size
        if n == 1:
            # Passthrough territory: nothing to plan, nothing to record.
            return kernels.bucket_exchange, ("single",)
        budget = getattr(Env.get().conf, "dense_hbm_budget", 4 << 30)
        plan = exchange_plan.plan_exchange(
            n_shards=n,
            capacity=max(b.capacity for b in blks),
            slot_capacity=slot_capacity,
            out_capacity=out_capacity,
            row_bytes=max(exchange_plan.block_row_bytes(b) for b in blks),
            budget_bytes=budget,
            mode=self.exchange_mode,
            blocks=[(b.capacity, exchange_plan.block_row_bytes(b))
                    for b in blks],
        )
        self._exchange_plan = plan
        exchange_plan.record_plan(plan)
        bus = getattr(self.context, "bus", None)
        if bus is not None:
            from vega_tpu.scheduler import events as ev

            bus.post(ev.DenseExchangePlanned(
                rdd_id=self.rdd_id, program=plan.program,
                rounds=plan.rounds, group=plan.group,
                est_peak_bytes=plan.est_peak_bytes,
                budget_bytes=budget, n_shards=n, fits=plan.fits,
            ))
        return exchange_plan.exchange_callable(plan), plan.cache_token()

    def _hash_histogram(self, blk: Block,
                        chain=()) -> Optional[np.ndarray]:
        """One cheap counting pass over the keys: hist[s, t] = rows shard s
        will send to target t under hash bucketing. Costs a hash + bincount
        per shard (no sort, no value movement) and one tiny [n, n]
        transfer; buys exactly-sized exchange capacities. `chain` is a
        fused narrow run applied to the root block's columns first (the
        exchange recomputes it too — cheaper than materializing)."""
        n = self.mesh.size
        if n == 1:
            return None
        chain = chain or ()
        # Without a fused chain the histogram only needs the key columns:
        # keep the program universal across value schemas (one compile)
        # and skip staging value columns it never reads.
        if chain:
            in_names = list(blk.cols)
        else:
            in_names = [KEY] + ([KEY_LO] if KEY_LO in blk.cols else [])

        def prog_fn(counts, *col_arrays):
            cols = dict(zip(in_names, col_arrays))
            cols, count = _apply_chain(chain, cols, counts[0])
            cap = cols[KEY].shape[0]
            bucket = _bucket_cols(cols, n)
            bucket = jnp.where(kernels.valid_mask(cap, count), bucket, n)
            return jnp.bincount(bucket, length=n + 1)[:n].astype(jnp.int32)

        prog = _cached_program(
            ("hash_hist", self.mesh, n, tuple(in_names), _chain_fp(chain)),
            lambda: _shard_program(self.mesh, prog_fn, 1 + len(in_names),
                                   _SPEC),
        )
        out = prog(blk.counts, *[blk.cols[nm] for nm in in_names])
        return np.asarray(mesh_lib.host_get(out)).reshape(n, n)

    def _range_histogram(self, blk: Block, bounds_dev,
                         ascending: bool, bounds_lo_dev=None,
                         chain=()) -> Optional[np.ndarray]:
        """Destination histogram under range partitioning (sort_by_key).
        bounds_lo_dev carries the low-word bounds of two-column int64
        keys; `chain` is a fused narrow run applied first."""
        n = self.mesh.size
        if n == 1:
            return None
        composite = bounds_lo_dev is not None
        chain = chain or ()
        if chain:
            in_names = list(blk.cols)
        else:
            in_names = [KEY] + ([KEY_LO] if composite else [])

        def prog_fn(*args):
            n_bounds = 1 + composite
            bnds = args[0]
            bnds_lo = args[1] if composite else None
            counts = args[n_bounds]
            cols = dict(zip(in_names, args[n_bounds + 1:]))
            cols, count = _apply_chain(chain, cols, counts[0])
            keys = cols[KEY]
            cap = keys.shape[0]
            bucket = kernels.range_bucket(
                bnds, keys, ascending, bounds_lo=bnds_lo,
                keys_lo=cols[KEY_LO] if composite else None,
            )
            bucket = jnp.where(kernels.valid_mask(cap, count), bucket, n)
            return jnp.bincount(bucket, length=n + 1)[:n].astype(jnp.int32)

        in_specs = ((_REPL,) * (1 + composite)
                    + (_SPEC,) * (1 + len(in_names)))
        prog = _cached_program(
            ("range_hist", self.mesh, n, ascending, composite,
             tuple(in_names), _chain_fp(chain)),
            lambda: _shard_program(self.mesh, prog_fn, in_specs, _SPEC),
        )
        args = ((bounds_dev,) + ((bounds_lo_dev,) if composite else ())
                + (blk.counts,)
                + tuple(blk.cols[nm] for nm in in_names))
        out = prog(*args)
        return np.asarray(mesh_lib.host_get(out)).reshape(n, n)

    def _hint_key(self, *extra):
        """Capacity-hint identity: structural lineage + fetch-free input
        size identity (_counts_fp — leaf counts, or materialized counts
        where already host-known). Same pipeline shape over same-size
        inputs (the steady-state rerun and the streamed per-chunk case)
        reuses last run's capacities and skips both the sizing histogram
        AND the post-launch overflow fetch (deferred to _settle_pending);
        a changed key distribution under equal counts surfaces at
        settlement, which repairs through the exact histogram."""
        return (self._lineage_fp(), self._counts_fp(), extra)

    def _run_exchange(self, build_program, counts,
                      hists: Optional[List[np.ndarray]] = None,
                      slot_hists: Optional[List[np.ndarray]] = None,
                      make_hists=None, hint_key=None, fixed_caps=None,
                      validate=None, on_success=None):
        """Run the fused exchange program with capacity sizing.

        Sizing order: (1) `fixed_caps` — capacities known a priori
        (elided passthroughs, which cannot overflow), (2) a memoized
        capacity hint for this lineage+sizes (no device work), (3) exact
        histograms — passed eagerly via `hists`/`slot_hists` or computed
        lazily by `make_hists()` (a device pass, skipped entirely on a
        hint hit), (4) the heuristic growth schedule; `counts` may be a
        callable so cold-path-only sizing inputs are never fetched on the
        warm path. Overflow at any stage falls through to the next.

        Deferred mode (fixed/hinted, unless a settle-repair is running):
        the program launches WITHOUT the blocking (counts, overflow)
        fetch — each such fetch is a full network RTT through the axon
        tunnel between otherwise async-pipelined launches — and leaves a
        pending entry for _attach_pending/_settle_pending to verify at
        the next genuine host read. `validate`/`on_success` ride the
        entry (join product checks / node bookkeeping)."""
        import time as _time

        from vega_tpu.scheduler import events as ev

        n = self.mesh.size
        hist_pair = (None if make_hists is not None
                     else (hists, slot_hists))
        ctx = self.context
        hint_store = ctx.__dict__.setdefault("_dense_capacity_hints", {})
        hinted = hint_key is not None and hint_key in hint_store
        bus = getattr(ctx, "bus", None)
        t_start = _time.time()
        if ((fixed_caps is not None or hinted)
                and not ctx.__dict__.get("_dense_no_defer")):
            slot, out_cap = (fixed_caps if fixed_caps is not None
                             else hint_store[hint_key])
            if bus is not None:
                bus.post(ev.StageSubmitted(
                    stage_id=-self.rdd_id, num_tasks=n, is_shuffle_map=True,
                ))
            try:
                prog, args = build_program(slot, out_cap)
                # Launch under the CPU dispatch door: a concurrent
                # device_get on another task thread (shard_rows /
                # host_get) deadlocks old XLA:CPU (mesh.device_door).
                with mesh_lib.device_door():
                    *outs, overflow = prog(*args)
            finally:
                if bus is not None:
                    # JAX dispatch is async: prog() returned but the device
                    # may still be executing — this timing is dispatch-only.
                    bus.post(ev.StageCompleted(
                        stage_id=-self.rdd_id,
                        duration_s=_time.time() - t_start,
                        speculative=True,
                    ))
            self._last_attempts = 1
            extra = getattr(self, "_fetch_extra_outs", 0)
            self._deferred_entry = {
                "rdd": self,
                "outs_head": tuple(outs[:1 + extra]),
                "overflow": overflow,
                "hint_key": None if fixed_caps is not None else hint_key,
                "caps": (slot, out_cap),
                "validate": validate,
                "on_success": on_success,
            }
            self._last_counts_host = None
            self._last_extra_host = None
            return outs, out_cap
        # Blocking path: before sizing from (or launching over) parent
        # data, settle the speculation backlog — histogram passes and the
        # heuristic's counts would otherwise trust possibly-truncated
        # blocks. Repairs rewrite failed blocks in place, so references
        # captured above this frame stay valid.
        _settle_pending(ctx)
        if bus is not None:
            # Dense stages bypass the task scheduler (one SPMD launch);
            # surface them on the same event bus for observability. One
            # Submitted/Completed pair per exchange, retries included.
            bus.post(ev.StageSubmitted(
                stage_id=-self.rdd_id, num_tasks=n, is_shuffle_map=True,
            ))
        try:
            attempt = 0  # histogram/heuristic growth step
            for round_i in range(6):
                if fixed_caps is not None and round_i == 0:
                    slot, out_cap = fixed_caps
                elif hinted and round_i == 0:
                    slot, out_cap = hint_store[hint_key]
                else:
                    if hist_pair is None:
                        hist_pair = make_hists()
                    hs = [h for h in (hist_pair[0] or []) if h is not None]
                    sh = hist_pair[1]
                    if sh is not None:
                        sh = [h for h in sh if h is not None]
                    if hs:
                        slot, out_cap = _histogram_capacities(hs, attempt,
                                                              sh)
                    else:
                        if callable(counts):
                            counts = counts()
                        slot, out_cap = _exchange_capacities(counts, n,
                                                             attempt)
                    attempt += 1
                prog, args = build_program(slot, out_cap)
                with mesh_lib.device_door():  # see the deferred launch
                    *outs, overflow = prog(*args)
                self._last_attempts = round_i + 1
                # One transfer for (counts, any extra driver-needed outputs,
                # overflow): each separate device_get is a full round trip
                # (a network RTT through the axon tunnel). Nodes that need
                # more outputs on the host (join's exact product sizes) set
                # _fetch_extra_outs to ride the same transfer.
                extra = getattr(self, "_fetch_extra_outs", 0)
                fetched, overflow_host = mesh_lib.host_get(
                    (tuple(outs[:1 + extra]), overflow)
                )
                if not bool(np.any(np.asarray(overflow_host))):
                    self._last_counts_host = np.asarray(
                        fetched[0]
                    ).reshape(-1)
                    self._last_extra_host = [np.asarray(x)
                                             for x in fetched[1:]]
                    if hint_key is not None:
                        # pop-then-insert refreshes recency: eviction pops
                        # the FRONT of the insertion-ordered dict, and the
                        # hot steady-state key (re-stored every warm run)
                        # must not be the one that goes.
                        hint_store.pop(hint_key, None)
                        hint_store[hint_key] = (slot, out_cap)
                        # Bound the store: data-dependent counts (filters,
                        # ragged tail chunks) mint fresh keys per run; drop
                        # oldest entries past the cap.
                        while len(hint_store) > 4096:
                            hint_store.pop(next(iter(hint_store)))
                    return outs, out_cap
                log.info("exchange overflow (slot=%d out=%d), retrying",
                         slot, out_cap)
            raise VegaError(
                "exchange capacity overflow after retries — key skew "
                "exceeds capacity growth; repartition or use host tier"
            )
        finally:
            if bus is not None:
                bus.post(ev.StageCompleted(
                    stage_id=-self.rdd_id, duration_s=_time.time() - t_start,
                ))


# Synthetic flag column tracking signed overflow of wide int64 adds through
# an exchange: injected before the map-side combine, OR-merged per key by
# _named_wide_combine, and collapsed to one per-shard flag output (the
# capacity-flag pattern applied to arithmetic).
_SOVF = "__sovf"


def _named_wide_combine(op: str, value_names, wide: dict,
                        ovf_name: Optional[str] = None):
    """Per-column combine for a named op over a mix of narrow columns and
    wide (hi, lo) int64 pairs: narrow columns use the plain monoid, wide
    pairs use carry addition / lexicographic select (kernels.wide_add /
    wide_select). With ovf_name (add only), the named column carries a
    sticky int32 flag OR-ing every pair-add's signed-overflow predicate —
    clean flags PROVE the mod-2^64 results equal the exact totals."""
    narrow_ops = {"add": jnp.add, "min": jnp.minimum, "max": jnp.maximum,
                  "prod": jnp.multiply}
    lo_names = set(wide.values())

    def combine(a, b):
        out = {}
        flag = None
        for nm in value_names:
            if nm in lo_names or nm == ovf_name:
                continue
            if nm in wide:
                lo = wide[nm]
                if op == "add":
                    if ovf_name is not None:
                        out[nm], out[lo], o = kernels.wide_add_checked(
                            a[nm], a[lo], b[nm], b[lo])
                        flag = o if flag is None else (flag | o)
                    else:
                        out[nm], out[lo] = kernels.wide_add(
                            a[nm], a[lo], b[nm], b[lo])
                else:  # min/max (prod is rejected at build time)
                    out[nm], out[lo] = kernels.wide_select(
                        a[nm], a[lo], b[nm], b[lo], op == "min")
            else:
                out[nm] = narrow_ops[op](a[nm], b[nm])
        if ovf_name is not None:
            f = a[ovf_name] | b[ovf_name]
            if flag is not None:
                f = f | flag.astype(f.dtype)
            out[ovf_name] = f
        return out

    return combine


class _ReduceByKeyRDD(_ExchangeRDD):
    @property
    def hash_placed(self) -> bool:
        """Output rows live on shard hash(key) % n — EXCEPT after a
        host-exact fold (wide-sum overflow takeover), which rebuilds with
        no device placement. PURE read: while unmaterialized the answer
        is a conservative False (a bare attribute read — repr, debug,
        monitoring — must not launch the exchange as a side effect);
        planners call _settle_placement() first for the materialized
        truth. block_spec() doesn't settle, and a later failed
        speculation invalidates dependents through _settle_pending's
        lineage walk, so an early post-materialization read stays
        sound."""
        if self._block is None:
            return False
        return not getattr(self, "_host_folded", False)

    @property
    def key_sorted(self) -> bool:
        """Segment ends come out in key order — except after a host-exact
        fold (same conservative-until-materialized read as hash_placed)."""
        if self._block is None:
            return False
        return not getattr(self, "_host_folded", False)

    def _settle_placement(self) -> None:
        self.block_spec()

    def __init__(self, parent: DenseRDD, op: Optional[str], func):
        super().__init__(parent.context, parent.mesh, [parent])
        self.parent = parent
        self._op = op
        pschema = parent._schema()
        self._value_names = [nm for nm, _ in pschema
                             if nm not in (KEY, KEY_LO)]
        if op == "prod" and \
                block_lib.wide_value_pairs(nm for nm, _ in pschema):
            # 64-bit product needs full 64x64 multiply emulation — not
            # worth a device path; int64 products overflow almost
            # immediately anyway. Keys decode on the host tier, so point
            # there.
            raise VegaError(
                "reduce_by_key(op='prod') over int64 (wide) values has no "
                "device path — use the host tier (.to_rdd()) for exact "
                "products"
            )
        if func is not None:
            if block_lib.wide_value_pairs(nm for nm, _ in pschema):
                # A traced binop would see encoded (hi, lo) words as two
                # separate int32 scalars — silently wrong. No row form ->
                # host tier (which folds real int64s).
                raise _NotTraceable(
                    "wide int64 value columns: no scalar row form")
            dtypes = dict(pschema)
            structs = [jax.ShapeDtypeStruct((), dtypes[nm])
                       for nm in self._value_names]
            # Single value column: func is scalar x scalar -> scalar.
            # Multi-column block: func is tuple x tuple -> tuple, one
            # scalar per value column (device mean/variance etc. without
            # leaving the columnar layout).
            arg = structs[0] if len(structs) == 1 else tuple(structs)
            try:
                out = jax.eval_shape(func, arg, arg)
            except Exception as e:  # noqa: BLE001
                raise _NotTraceable(str(e)) from e
            if len(structs) == 1:
                if not hasattr(out, "shape") or out.shape != ():
                    raise _NotTraceable("binop must return a scalar")
                if out.dtype != structs[0].dtype:
                    raise _NotTraceable(
                        f"binop changes the value dtype "
                        f"({structs[0].dtype} -> {out.dtype}); cast the "
                        "column first so the block schema stays truthful"
                    )
            else:
                if not (isinstance(out, tuple) and len(out) == len(structs)):
                    raise _NotTraceable(
                        f"binop over {len(structs)} value columns must "
                        f"return a {len(structs)}-tuple"
                    )
                for nm, s, o in zip(self._value_names, structs, out):
                    if getattr(o, "shape", None) != ():
                        raise _NotTraceable("binop outputs must be scalars")
                    if o.dtype != s.dtype:
                        raise _NotTraceable(
                            f"binop changes dtype of column {nm!r} "
                            f"({s.dtype} -> {o.dtype}); cast the column "
                            "first so the block schema stays truthful"
                        )
        self._func = func

    def _schema(self):
        return self.parent._schema()

    def _fp_extra(self):
        return (self._op or _fp(self._func), self.exchange_mode)

    def _segment_reduce(self, cols, count, presorted, sort_impl="xla"):
        lo_name = _lo_of(cols)
        if self._op is not None:
            wide = block_lib.wide_value_pairs(cols)
            if wide:
                # Wide int64 values can't ride the XLA segment ops (the
                # carry couples the two words) — same segmented scan the
                # traced combiners use, with the carry/lex combine. An
                # injected _SOVF column (add only) accumulates the
                # overflow flags through the scan.
                combine = _named_wide_combine(
                    self._op, [nm for nm in cols
                               if nm not in (KEY, KEY_LO)], wide,
                    ovf_name=_SOVF if _SOVF in cols else None)
                return kernels.segment_reduce_sorted(
                    cols, count, KEY, combine, presorted=presorted,
                    lo_name=lo_name, sort_impl=sort_impl,
                )
            return kernels.segment_reduce_named(
                cols, count, KEY, self._op, presorted=presorted,
                lo_name=lo_name, sort_impl=sort_impl,
            )
        f = self._func
        names = self._value_names
        if len(names) == 1:
            nm0 = names[0]

            def combine(a, b):
                return {nm0: f(a[nm0], b[nm0])}
        else:
            def combine(a, b):
                out = f(tuple(a[nm] for nm in names),
                        tuple(b[nm] for nm in names))
                return dict(zip(names, out))

        return kernels.segment_reduce_sorted(
            cols, count, KEY, combine, presorted=presorted, lo_name=lo_name,
            sort_impl=sort_impl,
        )

    def _host_exact_fold(self) -> Block:
        """Host-tier takeover after the device flagged a possible wide
        int64 sum overflow: fold exact Python bignums over the parent's
        decoded rows, then rebuild a block in THIS node's schema (wide
        pairs re-encoded). A clean rebuild means the flagged wrap was
        transient (reassociation) and the exact totals fit; totals beyond
        int64 are not representable on device and raise crisply — the
        host tier (.to_rdd()) keeps exact bignums. The rebuilt block has
        no device placement/order guarantees: hash_placed/key_sorted
        report the materialized truth, so downstream exchanges skip
        elision instead of trusting stale placement."""
        log.info("wide int64 device sum flagged overflow; "
                 "host-exact fold takes over")
        parent_cols = self.parent.block().to_numpy()  # wide pairs decoded
        schema = dict(self._schema())
        keys = np.asarray(parent_cols[KEY])
        keys_list = keys.tolist()
        vnames = [nm for nm in parent_cols if nm != KEY]
        slot_of: dict = {}
        for k in keys_list:
            if k not in slot_of:
                slot_of[k] = len(slot_of)
        i64 = np.iinfo(np.int64)
        out_cols: dict = {}
        if block_lib.KEY_LO in schema:
            hi, lo = block_lib.encode_i64(
                np.asarray(list(slot_of), dtype=np.int64))
            out_cols[KEY], out_cols[block_lib.KEY_LO] = hi, lo
        else:
            kdict = self.parent._dicts().get(KEY)
            if kdict is not None:
                # to_numpy DECODED a dictionary key to strings; re-encode
                # through the PARENT dictionary (every key is in it) so
                # the rebuilt codes stay in the lineage's code space —
                # from_numpy minting a fresh local dictionary here would
                # diverge from what _dicts() reports downstream.
                out_cols[KEY] = np.searchsorted(
                    kdict, np.asarray(list(slot_of), dtype=kdict.dtype),
                ).astype(dict_encoding.CODE_DTYPE)
            else:
                out_cols[KEY] = np.asarray(list(slot_of), dtype=keys.dtype)
        for nm in vnames:
            col = np.asarray(parent_cols[nm])
            if np.issubdtype(col.dtype, np.integer):
                acc = [0] * len(slot_of)
                for k, v in zip(keys_list, col.tolist()):
                    acc[slot_of[k]] += v  # exact python ints
            else:
                acc = [0.0] * len(slot_of)
                for k, v in zip(keys_list, col.tolist()):
                    acc[slot_of[k]] += v
            if block_lib.lo_of(nm) in schema:  # wide in this schema
                if any(v < i64.min or v > i64.max for v in acc):
                    raise VegaError(
                        f"reduce_by_key(op='add'): exact total of column "
                        f"{nm!r} exceeds the int64 range and cannot be "
                        "represented on device — use the host tier "
                        "(.to_rdd()) for exact bignum sums"
                    )
                hi, lo = block_lib.encode_i64(
                    np.asarray(acc, dtype=np.int64))
                out_cols[nm], out_cols[block_lib.lo_of(nm)] = hi, lo
            elif np.issubdtype(col.dtype, np.integer):
                # narrow int columns wrap to their dtype, matching the
                # device's modular arithmetic
                info = np.iinfo(np.dtype(schema[nm]))
                span = 1 << info.bits
                acc = [((v - info.min) % span) + info.min for v in acc]
                out_cols[nm] = np.asarray(acc, dtype=np.dtype(schema[nm]))
            else:
                out_cols[nm] = np.asarray(acc, dtype=np.dtype(schema[nm]))
        self._host_folded = True
        return block_lib.from_numpy(out_cols, self.mesh)

    def _materialize(self) -> Block:
        n = self.mesh.size
        # Partitioner-equality elision, device edition: a hash-placed
        # parent already has every key's rows on their reducer shard, so
        # the whole exchange (hash + multi-key sort + collective)
        # collapses to one per-shard segment reduce — zero collectives.
        self.parent._settle_placement()  # materialized truth, explicitly
        elide = self.parent.hash_placed and n > 1
        # Order survives the elided passthrough's stable compact, letting
        # the reduce run presorted (no sort at all in reduce-of-reduce).
        elide_sorted = elide and self.parent.key_sorted
        # Fuse any pending narrow chain above the exchange into its own
        # program: the map/filter work rides the exchange launch instead
        # of materializing an intermediate block (one launch saved + no
        # intermediate HBM traffic; the sizing histogram recomputes the
        # chain — narrow work is cheap VPU math by construction). Fusion
        # only applies when a real exchange sizes itself from a histogram
        # of post-chain rows: elided and single-shard paths size from raw
        # counts, so a fused FILTER would leave them permanently
        # oversized — those materialize the parent as before.
        chain, root = (_narrow_chain(self.parent) if n > 1 and not elide
                       else ([], self.parent))
        chain = _detached_chain(chain)  # cached program must not pin nodes
        blk = root.block_spec()  # we register our own pending entry
        in_names = list(blk.cols)
        names = [nm for nm, _ in self.parent._schema()]
        sort_impl = _sort_impl()
        this = _detach(self)  # _segment_reduce state without the node
        # Wide int64 adds track signed overflow through the whole exchange
        # (the capacity-flag pattern applied to arithmetic): an injected
        # _SOVF column rides pre-combine -> exchange -> merge, collapses
        # to one per-shard flag fetched with the counts, and a set flag
        # routes to the host-exact fold (see _host_exact_fold).
        track_sovf = self._op == "add" and bool(
            block_lib.wide_value_pairs(names))
        from vega_tpu.env import Env as _Env

        # Per-backend resolution from measured evidence (env.py notes;
        # docs/BENCH_NOTES.md round 5). A typo'd value raising (rather
        # than silently running the default) keeps A/Bs honest — a
        # scarce tunnel-window job must never measure fused vs fused.
        plan = kernels.resolve_backend_mode(
            "dense_rbk_plan",
            getattr(_Env.get().conf, "dense_rbk_plan", "auto"),
            ("auto", "fused_sort", "sort_partition"),
            "sort_partition", "fused_sort")

        # ---- speculative dense-key TABLE plan (round 5) --------------
        # When a prior run of this lineage+sizes OBSERVED a small key
        # range [kmin, kmax] (learned for free off the standard
        # program's output keys, riding the counts fetch), the whole
        # reduce collapses to a per-shard scatter into a dense table +
        # ONE psum + a per-shard hash-mask compact: no sort, no row
        # exchange, and the output arrives hash-placed AND key-sorted.
        # Entirely speculative and SOUND (CLAUDE.md: no value probing
        # may select a fast path unguarded): the program flags any valid
        # key outside the hinted range — checked on the raw key values,
        # never via wrap-prone subtraction — or an output-capacity
        # overflow, and a set flag settles through the normal
        # _settle_pending repair, which re-runs under _dense_no_defer
        # where this plan is gated off. Gated to named add/min/max over
        # ONE narrow 32-bit value column with a single int32 key.
        schema_d = dict(self._schema())
        vname = (self._value_names[0]
                 if len(self._value_names) == 1 else None)
        # CPU-only until the on-chip A/B decides (env.py note).
        table_mode = kernels.resolve_backend_mode(
            "dense_table_plan",
            getattr(_Env.get().conf, "dense_table_plan", "auto"),
            ("auto", "on", "off"), "on", "off")
        # Learning is gated on the mode too: with the plan off, the
        # extra kmin/kmax outputs and their fetch would be pure dead
        # work on every eligible reduce (cache-safe: learn_range is in
        # the program-cache key).
        learn_range = (
            table_mode == "on"
            and self._op in ("add", "min", "max") and vname is not None
            and not track_sovf and KEY_LO not in schema_d
            and jnp.dtype(schema_d[vname]) in (jnp.dtype(jnp.int32),
                                               jnp.dtype(jnp.float32)))
        range_hints = self.context.__dict__.setdefault(
            "_dense_key_range_hints", {})
        table_range = None
        if learn_range and not elide \
                and not self.context.__dict__.get("_dense_no_defer"):
            rh = range_hints.get(self._hint_key())
            if rh is not None:
                kmin_h, kmax_h = rh
                # Bucket the range so drifting hints (streamed chunks
                # whose keys slide run to run) reuse one compiled
                # program instead of minting a fresh _PROGRAM_CACHE
                # entry per observed range: align kmin down to 4K and
                # round the spread to a capacity bucket. A WIDER table
                # is trivially sound — extra slots end with cnt==0 and
                # emit nothing — and the range check covers the widened
                # bounds, so it only gets laxer, never wrong.
                kmin_b = (int(kmin_h) >> 12) << 12  # floor, sign-safe
                spread_b = block_lib._round_capacity(
                    int(kmax_h) - kmin_b + 1)
                # Table work is O(spread) per shard (+ an O(spread)
                # psum): require it comfortably under the input size and
                # an absolute cap (32 MB of table+counts per shard).
                if 0 < spread_b <= min(1 << 22, 2 * blk.capacity * n) \
                        and kmin_b + spread_b - 1 <= np.iinfo(np.int32).max:
                    table_range = (kmin_b, spread_b)

        if table_range is not None:
            kmin_c, spread = table_range
            op = self._op
            vdt = jnp.dtype(schema_d[vname])
            out_cap_t = block_lib._round_capacity(
                min(spread, int(spread / max(n, 1) * 1.3) + 128))

            def table_prog(counts, *col_arrays):
                cols = dict(zip(in_names, col_arrays))
                cols, count = _apply_chain(chain, cols, counts[0])
                keys = cols[KEY]
                vals = cols[vname]
                vdt_t = vals.dtype  # trace-time truth, never closure bake
                cap = keys.shape[0]
                maskv = kernels.valid_mask(cap, count)
                in_rng = ((keys >= jnp.int32(kmin_c))
                          & (keys <= jnp.int32(kmin_c + spread - 1)))
                bad = jnp.any(maskv & ~in_rng)
                ok = maskv & in_rng
                # Dropped rows (invalid or out-of-range) scatter to the
                # out-of-bounds slot `spread`, which mode="drop" ignores.
                idx = jnp.where(ok, keys - jnp.int32(kmin_c),
                                jnp.int32(spread))
                if op == "add":
                    tbl = jnp.zeros((spread,), vdt_t)
                    tbl = tbl.at[idx].add(vals, mode="drop")
                elif op == "min":
                    init = (jnp.inf if vdt_t == jnp.dtype(jnp.float32)
                            else jnp.iinfo(jnp.int32).max)
                    tbl = jnp.full((spread,), init, vdt_t)
                    tbl = tbl.at[idx].min(vals, mode="drop")
                else:
                    init = (-jnp.inf if vdt_t == jnp.dtype(jnp.float32)
                            else jnp.iinfo(jnp.int32).min)
                    tbl = jnp.full((spread,), init, vdt_t)
                    tbl = tbl.at[idx].max(vals, mode="drop")
                cnt = jnp.zeros((spread,), jnp.int32)
                cnt = cnt.at[idx].add(1, mode="drop")
                tbl = jax.lax.psum(tbl, mesh_lib.SHARD_AXIS)
                cnt = jax.lax.psum(cnt, mesh_lib.SHARD_AXIS)
                keys_all = jnp.int32(kmin_c) + lax.iota(jnp.int32, spread)
                me = jax.lax.axis_index(mesh_lib.SHARD_AXIS)
                mine = ((_bucket_cols({KEY: keys_all}, n) == me)
                        & (cnt > 0))  # absent keys must not emit rows
                out, out_count = kernels.compact(
                    {KEY: keys_all, vname: tbl}, mine, out_cap_t)
                overflow = bad | (out_count > jnp.int32(out_cap_t))
                return (out_count.reshape(1), out[KEY], out[vname],
                        overflow.reshape(1).astype(jnp.int32))

            prog = _cached_program(
                ("rbk_table", self.mesh, tuple(in_names), vname,
                 str(vdt), _chain_fp(chain), n, out_cap_t, kmin_c,
                 spread, op),
                lambda: _shard_program(self.mesh, table_prog,
                                       1 + len(in_names), (_SPEC,) * 4),
            )
            # The gate above checked _dense_no_defer, but a CONCURRENT
            # thread's settlement repair may have set it since: re-check
            # immediately before launch and fall through to the standard
            # plan if so. Without this, _run_exchange would take its
            # blocking retry loop, whose grown capacities this build
            # lambda ignores — six identical fixed-caps launches ending in
            # a spurious VegaError instead of a plan fallback.
            if self.context.__dict__.get("_dense_no_defer"):
                table_range = None
        if table_range is not None:
            # The gate guarantees _dense_no_defer is off, so this is
            # exactly _run_exchange's deferred fixed-caps launch — bus
            # events, the pending entry, and settlement/repair all ride
            # the shared choreography (a failed flag repairs through the
            # standard plan: the rerun holds _dense_no_defer).
            self._fetch_extra_outs = 0
            self._elided = False
            self._table_plan = True  # observability/tests
            outs_t, _ = self._run_exchange(
                lambda slot, cap: (
                    prog, (blk.counts,
                           *[blk.cols[nm] for nm in in_names])),
                lambda: blk.counts_np,
                fixed_caps=(0, out_cap_t),
            )
            t_counts, t_keys, t_vals = outs_t
            return self._attach_pending(Block(
                cols={KEY: t_keys, vname: t_vals}, counts=t_counts,
                capacity=out_cap_t, mesh=self.mesh,
                counts_host=self._last_counts_host))
        self._table_plan = False

        def build(slot, out_cap):
            exchange, x_tok = ((kernels.bucket_exchange, _X_ELIDED)
                               if elide else
                               self._resolve_exchange((blk,), slot,
                                                      out_cap))

            def prog_fn(counts, *col_arrays):
                cols = dict(zip(in_names, col_arrays))
                cols, count = _apply_chain(chain, cols, counts[0])
                if track_sovf:
                    cols[_SOVF] = jnp.zeros(cols[KEY].shape[0], jnp.int32)
                if n > 1 and not elide and plan == "sort_partition":
                    # Alternative plan: key-only sort -> presorted
                    # map-side combine -> stable counting partition of
                    # the (often much smaller) combined rows. Equal keys
                    # share a bucket by hash determinism, so combining
                    # across bucket boundaries is safe.
                    cols = kernels.sort_by_column(
                        cols, count, KEY, lo_name=_lo_of(cols),
                        impl=sort_impl)
                    cols, count = this._segment_reduce(
                        cols, count, presorted=True, sort_impl=sort_impl)
                    capacity = cols[KEY].shape[0]
                    mask = kernels.valid_mask(capacity, count)
                    bucket = _bucket_cols(cols, n)
                    bucket = jnp.where(mask, bucket, n)
                    # counting-path intermediates are O(capacity * n):
                    # bound them (~256 MiB) on big blocks via the argsort
                    # escape so the plan can't OOM where fused_sort won't
                    low_mem = capacity * (n + 1) * 4 > (256 << 20)
                    cols, bucket = kernels.partition_by_bucket(
                        cols, bucket, n, prefer_low_memory=low_mem,
                        sort_impl=sort_impl)
                    cols, count, overflow = exchange(
                        cols, count, bucket, n, slot, out_cap,
                        pregrouped=True,
                    )
                elif n > 1 and not elide:
                    # 2-sort exchange: ONE multi-key sort (bucket major,
                    # key minor) feeds both the presorted map-side combine
                    # (reference: dependency.rs:176-223) and a pregrouped
                    # exchange — vs the 3 sorts of sort-for-combine +
                    # group-by-bucket + reduce-side sort.
                    capacity = cols[KEY].shape[0]
                    mask = kernels.valid_mask(capacity, count)
                    bucket = _bucket_cols(cols, n)
                    bucket = jnp.where(mask, bucket, n)
                    cols, bucket = kernels.bucket_key_sort(
                        cols, count, bucket, KEY, lo_name=_lo_of(cols),
                        impl=sort_impl, n_shards=n,
                    )
                    cols, count = this._segment_reduce(
                        cols, count, presorted=True, sort_impl=sort_impl)
                    # compact kept (bucket, key) order; re-derive the
                    # combiner rows' buckets from their keys (hash is cheap
                    # and deterministic).
                    bucket = _bucket_cols(cols, n)
                    cols, count, overflow = exchange(
                        cols, count, bucket, n, slot, out_cap,
                        pregrouped=True,
                    )
                elif not elide:
                    bucket = jnp.zeros_like(cols[KEY])
                    cols, count, overflow = exchange(
                        cols, count, bucket, n, slot, out_cap,
                        sort_impl=sort_impl,
                    )
                else:
                    capacity = cols[KEY].shape[0]
                    cols, count, overflow = kernels.passthrough_exchange(
                        cols, count, capacity, out_cap
                    )
                # reduce-side merge (reference: shuffled_rdd.rs:149-170)
                cols, count = this._segment_reduce(
                    cols, count, presorted=elide_sorted,
                    sort_impl=sort_impl)
                res = (count.reshape(1),)
                if track_sovf:
                    m = kernels.valid_mask(cols[_SOVF].shape[0], count)
                    sovf = jnp.any(jnp.where(m, cols[_SOVF], 0) != 0)
                    res += (sovf.reshape(1).astype(jnp.int32),)
                if learn_range:
                    # Observed key range of the OUTPUT (same min/max as
                    # the input keys), riding the counts fetch for free:
                    # feeds the table plan's hint for the next warm run.
                    mo = kernels.valid_mask(cols[KEY].shape[0], count)
                    res += (
                        jnp.min(jnp.where(
                            mo, cols[KEY],
                            jnp.iinfo(jnp.int32).max)).reshape(1),
                        jnp.max(jnp.where(
                            mo, cols[KEY],
                            jnp.iinfo(jnp.int32).min)).reshape(1),
                    )
                return res + tuple(
                    cols[nm] for nm in names
                ) + (overflow.reshape(1),)

            key = ("rbk", self.mesh, tuple(in_names), tuple(names),
                   _chain_fp(chain), n, slot, out_cap, elide, elide_sorted,
                   self.exchange_mode, x_tok, self._op or _fp(self._func),
                   track_sovf, learn_range, plan, sort_impl)
            prog = _cached_program(
                key,
                lambda: _shard_program(
                    self.mesh, prog_fn, 1 + len(in_names),
                    (_SPEC,) * (2 + track_sovf + 2 * learn_range
                                + len(names)),
                ),
            )
            return prog, (blk.counts, *[blk.cols[nm] for nm in in_names])

        # Elided: rows stay put, so capacities are known a priori (no
        # sizing pass, no overflow possible): tight when the parent's
        # counts are already host-known, else the parent's capacity —
        # never a fetch. Slot is unused by the passthrough.
        self._elided = elide
        # sovf / learned-key-range ride the (counts, overflow) transfer;
        # deferred launches re-check sovf at settlement via validate.
        extra_n = (1 if track_sovf else 0) + (2 if learn_range else 0)
        self._fetch_extra_outs = extra_n
        validate = ((lambda head: not bool(np.any(np.asarray(head[1]))))
                    if track_sovf else None)

        def bank_range(lo_arr, hi_arr):
            # Per-shard sentinels (empty shards report int32 max/min)
            # fall out of the global min/max.
            kmin_o = int(np.asarray(lo_arr).min())
            kmax_o = int(np.asarray(hi_arr).max())
            if kmin_o <= kmax_o:
                hk = self._hint_key()
                range_hints.pop(hk, None)
                range_hints[hk] = (kmin_o, kmax_o)
                while len(range_hints) > 4096:
                    range_hints.pop(next(iter(range_hints)))

        # Deferred launches bank the range at settlement commit —
        # without this, an evicted range hint under a live capacity hint
        # would pay for the two extra outputs forever while the table
        # plan never re-activates.
        on_success = ((lambda head: bank_range(head[-2], head[-1]))
                      if learn_range else None)
        if elide:
            outs, out_cap = self._run_exchange(
                build, lambda: blk.counts_np,
                fixed_caps=(0, _elide_out_cap(blk)),
                validate=validate, on_success=on_success,
            )
        else:
            outs, out_cap = self._run_exchange(
                build, lambda: blk.counts_np,
                make_hists=lambda: ([self._hash_histogram(blk, chain)],
                                    None),
                hint_key=self._hint_key(),
                validate=validate, on_success=on_success,
            )
        counts, col_arrays = outs[0], outs[1 + extra_n:]
        extra = self._last_extra_host
        if track_sovf and extra and np.any(np.asarray(extra[0])):
            # Blocking path saw the flag inline (the deferred path
            # reaches here via _settle_pending's repair rerun).
            return self._host_exact_fold()
        if learn_range and extra is not None and len(extra) >= 2:
            # Blocking path: bank inline (deferred banks via on_success).
            bank_range(extra[-2], extra[-1])
        return self._attach_pending(Block(
            cols=dict(zip(names, col_arrays)), counts=counts,
            capacity=out_cap, mesh=self.mesh,
            counts_host=self._last_counts_host))


class _GroupByKeyRDD(_ExchangeRDD):
    """Exchange + local sort; block holds key-sorted runs per shard."""

    hash_placed = True  # output rows live on shard hash(key) % n
    key_sorted = True   # the whole point of the grouped block

    def __init__(self, parent: DenseRDD):
        super().__init__(parent.context, parent.mesh, [parent])
        self.parent = parent

    def _schema(self):
        return self.parent._schema()

    def _fp_extra(self):
        return (self.exchange_mode,)

    def _materialize(self) -> Block:
        n = self.mesh.size
        self.parent._settle_placement()  # materialized truth, explicitly
        elide = self.parent.hash_placed and n > 1  # rows already placed
        elide_sorted = elide and self.parent.key_sorted
        # Fused only on the real-exchange path (see reduce: elided/1-shard
        # sizing uses raw counts, which a fused filter would inflate).
        chain, root = (_narrow_chain(self.parent) if n > 1 and not elide
                       else ([], self.parent))
        chain = _detached_chain(chain)  # cached program must not pin nodes
        blk = root.block_spec()  # we register our own pending entry
        in_names = list(blk.cols)
        names = [nm for nm, _ in self.parent._schema()]
        sort_impl = _sort_impl()

        def build(slot, out_cap):
            exchange, x_tok = ((kernels.bucket_exchange, _X_ELIDED)
                               if elide else
                               self._resolve_exchange((blk,), slot,
                                                      out_cap))

            def prog_fn(counts, *col_arrays):
                cols = dict(zip(in_names, col_arrays))
                cols, count = _apply_chain(chain, cols, counts[0])
                if elide:
                    cols, count, overflow = kernels.passthrough_exchange(
                        cols, count, cols[KEY].shape[0], out_cap
                    )
                else:
                    bucket = (_bucket_cols(cols, n)
                              if n > 1 else jnp.zeros_like(cols[KEY]))
                    cols, count, overflow = exchange(
                        cols, count, bucket, n, slot, out_cap,
                        sort_impl=sort_impl,
                    )
                if not elide_sorted:  # already sorted rows skip the sort
                    cols = kernels.sort_by_column(cols, count, KEY,
                                                  lo_name=_lo_of(cols),
                                                  impl=sort_impl)
                return (count.reshape(1),) + tuple(
                    cols[nm] for nm in names
                ) + (overflow.reshape(1),)

            key = ("gbk", self.mesh, tuple(in_names), tuple(names),
                   _chain_fp(chain), n, slot, out_cap, elide,
                   elide_sorted, self.exchange_mode, x_tok, sort_impl)
            prog = _cached_program(
                key,
                lambda: _shard_program(
                    self.mesh, prog_fn, 1 + len(in_names),
                    (_SPEC,) * (2 + len(names)),
                ),
            )
            return prog, (blk.counts, *[blk.cols[nm] for nm in in_names])

        self._elided = elide
        if elide:
            outs, out_cap = self._run_exchange(
                build, lambda: blk.counts_np,
                fixed_caps=(0, _elide_out_cap(blk)),
            )
        else:
            outs, out_cap = self._run_exchange(
                build, lambda: blk.counts_np,
                make_hists=lambda: ([self._hash_histogram(blk, chain)],
                                    None),
                hint_key=self._hint_key(),
            )
        counts, col_arrays = outs[0], outs[1:]
        return self._attach_pending(Block(
            cols=dict(zip(names, col_arrays)), counts=counts,
            capacity=out_cap, mesh=self.mesh,
            counts_host=self._last_counts_host))

    def collect_grouped(self):
        """Columnar grouped collect: (keys, offsets, values) numpy arrays,
        where group i's values are values[offsets[i]:offsets[i+1]] — the
        ragged result WITHOUT per-key Python lists (group_by_key's scale
        face; reference aggregator.rs:33-53 builds Vecs instead). Shards are
        key-sorted and hash-disjoint, so boundaries fall out of one
        vectorized pass over the concatenated rows."""
        cols = self.block().to_numpy()
        return _grouped_columnar(cols[KEY], cols[VALUE])

    def collect(self) -> list:
        # keys are sorted within each shard; shards don't overlap (hash
        # partitioned), so grouping is a single pass per shard run.
        cols = self.block().to_numpy()
        return list(_sorted_runs(cols[KEY], cols[VALUE]))

    def compute(self, split: Split, task_context=None):
        rows = self.block().shard_rows(split.index)
        yield from _sorted_runs(rows[KEY], rows[VALUE])


class _JoinRDD(_ExchangeRDD):
    """Device sort-merge join with full duplicate-key semantics (dup x dup
    product, reference pair_rdd.rs:104-121) — no host fallback on the dense
    path. Output expansion beyond the exchange capacity is reported exactly
    by the kernel and rerun once at the right capacity. A hash-placed side
    (e.g. a reduce_by_key output) skips its exchange entirely."""

    hash_placed = True  # joined rows stay on their key's shard
    key_sorted = True   # output follows the left sort order

    def __init__(self, left: DenseRDD, right: DenseRDD,
                 outer: bool = False, fill_value=0):
        super().__init__(left.context, left.mesh, [left, right])
        self.left = left
        self.right = right
        self.outer = outer
        self.fill_value = fill_value

    def _fp_extra(self):
        # repr() keeps NaN fills hint-stable (nan != nan would make every
        # hint lookup miss and leak a store entry per run).
        return (self.outer, repr(self.fill_value), self.exchange_mode)

    @staticmethod
    def _side_value_names(schema):
        """Value-column names of one side in schema order — VALUE plus its
        wide low word when the side carries int64 values."""
        return [nm for nm, _ in schema if nm not in (KEY, KEY_LO)]

    def _schema(self):
        ls = dict(self.left._schema())
        key_schema = ((KEY, ls[KEY]),)
        if KEY_LO in ls:
            key_schema += ((KEY_LO, ls[KEY_LO]),)
        out = key_schema
        for prefix, side in (("lv", self.left), ("rv", self.right)):
            for nm, dt in side._schema():
                if nm in (KEY, KEY_LO):
                    continue
                out += ((_join_rename(nm, prefix), dt),)
        return out

    def _dicts(self):
        # KEY: both sides were unified by _align_keys before construction
        # (or never diverged), so the left side's key dictionary IS the
        # shared one. Values: each side's dictionary follows its column
        # through the lv/rv rename.
        out = {}
        ld, rd = self.left._dicts(), self.right._dicts()
        if KEY in ld:
            out[KEY] = ld[KEY]
        for prefix, side_d, side in (("lv", ld, self.left),
                                     ("rv", rd, self.right)):
            for nm, _dt in side._schema():
                if nm in (KEY, KEY_LO) or nm not in side_d:
                    continue
                out[_join_rename(nm, prefix)] = side_d[nm]
        return out

    def _materialize(self) -> Block:
        n = self.mesh.size
        # Per-side exchange elision: a hash-placed side's rows are already
        # on their key's shard (reduce/group/join outputs), so only the
        # other side moves — the north-star reduced.join(table) pipeline
        # pays ONE collective instead of two.
        self.left._settle_placement()   # materialized truth, explicitly
        self.right._settle_placement()
        l_elide = self.left.hash_placed and n > 1
        r_elide = self.right.hash_placed and n > 1
        # Pending narrow chains fuse into the join program (same
        # rematerialization trade as reduce/group) — only on sides whose
        # exchange sizes from a post-chain histogram; elided/1-shard
        # sides size from raw counts and materialize as before.
        l_chain, l_root = (_narrow_chain(self.left)
                           if n > 1 and not l_elide else ([], self.left))
        r_chain, r_root = (_narrow_chain(self.right)
                           if n > 1 and not r_elide else ([], self.right))
        # cached program must not pin nodes
        l_chain = _detached_chain(l_chain)
        r_chain = _detached_chain(r_chain)
        outer, fill_value = self.outer, self.fill_value
        sort_impl = _sort_impl()
        lblk = l_root.block_spec()  # we register our own pending entry
        rblk = r_root.block_spec()
        l_in = list(lblk.cols)
        r_in = list(rblk.cols)
        # Key layout is aligned by _align_keys before a _JoinRDD is built:
        # both sides carry the same key columns (single, or (KEY, KEY_LO)).
        lschema = dict(self.left._schema())
        key_names = [KEY] + ([KEY_LO] if KEY_LO in lschema else [])
        lo_name = KEY_LO if KEY_LO in lschema else None
        l_val_names = self._side_value_names(self.left._schema())
        r_val_names = self._side_value_names(self.right._schema())
        n_vals = len(l_val_names) + len(r_val_names)
        # Sortedness survives only the elided (stable passthrough) path.
        l_sorted = l_elide and self.left.key_sorted
        r_sorted = r_elide and self.right.key_sorted
        join_cap_override: List[Optional[int]] = [None]
        join_cap_used: List[int] = [0]
        n_l = 1 + len(l_in)  # counts + left root columns

        def one_side(cols, count, elide, slot_pair, out_cap, exchange):
            if elide:
                return kernels.passthrough_exchange(
                    cols, count, cols[KEY].shape[0], out_cap
                )
            bucket = (_bucket_cols(cols, n)
                      if n > 1 else jnp.zeros_like(cols[KEY]))
            return exchange(cols, count, bucket, n, slot_pair, out_cap,
                            sort_impl=sort_impl)

        def build(slot_pair, out_cap):
            join_cap = join_cap_override[0] or out_cap
            join_cap_used[0] = join_cap
            if l_elide and r_elide:
                exchange, x_tok = kernels.bucket_exchange, _X_ELIDED
            else:
                moving = [b for b, el in ((lblk, l_elide), (rblk, r_elide))
                          if not el]
                exchange, x_tok = self._resolve_exchange(
                    moving, slot_pair, out_cap)

            def prog_fn(*args):
                lc, *lkv = args[:n_l]
                rc, *rkv = args[n_l:]
                lcols, lcount = _apply_chain(
                    l_chain, dict(zip(l_in, lkv)), lc[0]
                )
                rcols, rcount = _apply_chain(
                    r_chain, dict(zip(r_in, rkv)), rc[0]
                )
                lcols, lcount, lof = one_side(
                    lcols, lcount, l_elide, slot_pair, out_cap, exchange
                )
                rcols, rcount, rof = one_side(
                    rcols, rcount, r_elide, slot_pair, out_cap, exchange
                )
                joined, jcount, jtotal = kernels.merge_join_expand(
                    lcols, lcount, rcols, rcount, KEY, join_cap,
                    outer=outer, fill_value=fill_value,
                    left_sorted=l_sorted, right_sorted=r_sorted,
                    lo_name=lo_name, sort_impl=sort_impl,
                )
                return (
                    jcount.reshape(1), jtotal.reshape(1),
                ) + tuple(joined[nm] for nm in key_names) + tuple(
                    joined[nm] for nm in l_val_names
                ) + tuple(
                    joined[f"r_{nm}"] for nm in r_val_names
                ) + ((lof | rof).reshape(1),)

            prog = _cached_program(
                ("join", self.mesh, n, tuple(key_names), tuple(l_in),
                 tuple(r_in), _chain_fp(l_chain), _chain_fp(r_chain),
                 slot_pair, out_cap,
                 join_cap, l_elide, r_elide, l_sorted, r_sorted,
                 self.exchange_mode, x_tok, self.outer,
                 repr(self.fill_value), sort_impl),
                lambda: _shard_program(
                    self.mesh, prog_fn, 2 + len(l_in) + len(r_in),
                    (_SPEC,) * (3 + len(key_names) + n_vals)),
            )
            return prog, (
                lblk.counts, *[lblk.cols[nm] for nm in l_in],
                rblk.counts, *[rblk.cols[nm] for nm in r_in],
            )

        counts_fn = lambda: np.concatenate([lblk.counts_np, rblk.counts_np])
        self._elided = (l_elide, r_elide)
        self._fetch_extra_outs = 1  # jtotals rides the counts transfer

        def make_hists():
            # Blocking path only (post-settle), so counts_np is safe/free.
            hs = [
                np.diag(lblk.counts_np) if l_elide
                else self._hash_histogram(lblk, l_chain),
                np.diag(rblk.counts_np) if r_elide
                else self._hash_histogram(rblk, r_chain),
            ]
            # Elided (diag) sides never send: keep them out of slot sizing.
            return hs, [h for h, el in zip(hs, (l_elide, r_elide))
                        if not el]

        hint = self._hint_key()
        # The dup x dup product size is also hint-memoized: without it, a
        # join whose product exceeds the exchange-sized cap would repeat
        # its full-launch resize on every warm rerun.
        hint_store = self.context.__dict__.setdefault(
            "_dense_capacity_hints", {})
        jc_key = (hint, "join_cap")
        if jc_key in hint_store:
            join_cap_override[0] = hint_store[jc_key]

        def validate(head):
            """Deferred-mode product checks (the blocking path's inline
            logic below, recast for _settle_pending)."""
            jtot = int(head[1].max(initial=0))
            if jtot >= 2**31 - 1:
                raise VegaError(
                    "dense join product exceeds 2^31 rows on one shard — "
                    "cannot materialize; filter or pre-aggregate the "
                    "heavy keys"
                )
            if jtot > join_cap_used[0]:
                # Stash the exact product cap for the settle-repair rerun.
                hint_store[jc_key] = _cap_round(jtot)
                return False
            return True

        def on_success(_head):
            if join_cap_override[0]:
                hint_store.pop(jc_key, None)  # move-to-end (recency)
                hint_store[jc_key] = join_cap_override[0]
                while len(hint_store) > 4096:
                    hint_store.pop(next(iter(hint_store)))

        outs, _ = self._run_exchange(build, counts_fn,
                                     make_hists=make_hists,
                                     hint_key=hint, validate=validate,
                                     on_success=on_success)
        if "_deferred_entry" not in self.__dict__:
            # Blocking path: run the same product checks the deferred
            # entry runs at settlement (ONE policy, validate above). On a
            # cap miss, validate stashed the exact product cap under
            # jc_key; ONE resized rerun is guaranteed to fit (the kernel
            # reported the exact size — no geometric-growth walk).
            if not validate([None, self._last_extra_host[0]]):
                join_cap_override[0] = hint_store[jc_key]
                outs, _ = self._run_exchange(build, counts_fn,
                                             make_hists=make_hists,
                                             hint_key=hint,
                                             validate=validate,
                                             on_success=on_success)
            if "_deferred_entry" not in self.__dict__ \
                    and join_cap_override[0]:
                on_success(None)
        jcounts = outs[0]
        key_arrays = outs[2:2 + len(key_names)]
        val_arrays = outs[2 + len(key_names):2 + len(key_names) + n_vals]
        out_names = ([_join_rename(nm, "lv") for nm in l_val_names]
                     + [_join_rename(nm, "rv") for nm in r_val_names])
        cols = dict(zip(key_names, key_arrays))
        cols.update(dict(zip(out_names, val_arrays)))
        return self._attach_pending(Block(
            cols=cols,
            counts=jcounts, capacity=join_cap_used[0], mesh=self.mesh,
            counts_host=self._last_counts_host,
        ))

    @staticmethod
    def _rows(cols: dict):
        # to_numpy/shard_rows decode wide (lv, lv.lo) pairs to int64
        # before this zip, so lv/rv are single columns again.
        return (
            (k, (lv, rv))
            for k, lv, rv in zip(
                cols[KEY].tolist(), cols["lv"].tolist(), cols["rv"].tolist()
            )
        )

    def collect(self) -> list:
        return list(self._rows(self.block().to_numpy()))

    def count(self) -> int:
        return self.block().num_rows

    def compute(self, split: Split, task_context=None):
        yield from self._rows(self.block().shard_rows(split.index))


class _SortByKeyRDD(_ExchangeRDD):
    def __init__(self, parent: DenseRDD, ascending: bool, sample_size: int):
        super().__init__(parent.context, parent.mesh, [parent])
        self.parent = parent
        self.ascending = ascending
        self.sample_size = sample_size

    def _fp_extra(self):
        return (self.ascending, self.sample_size, self.exchange_mode)

    def _schema(self):
        return self.parent._schema()

    def _materialize(self) -> Block:
        n = self.mesh.size
        # Fused only on the multi-shard path (1-shard sizing uses raw
        # counts; see reduce). The range exchange itself never elides.
        chain, root = (_narrow_chain(self.parent) if n > 1
                       else ([], self.parent))
        chain = _detached_chain(chain)  # cached program must not pin nodes
        blk = root.block()
        in_names = list(blk.cols)
        names = [nm for nm, _ in self.parent._schema()]
        lo_name = _lo_of(names)
        composite = lo_name is not None
        # Sampler inputs: key columns only when no chain is fused (one
        # universal compile across value schemas, like the histograms).
        samp_in = (in_names if chain
                   else [KEY] + ([KEY_LO] if composite else []))

        # Bound sampling: ONE device program applies the fused chain and
        # gathers a strided sample per shard into a fixed [n_shards, 2m]
        # buffer, fetched with the post-chain shard counts in a single
        # transfer — the per-shard host slicing this replaces cost one
        # driver<->device round trip PER SHARD (n RTTs through the
        # tunnel). Post-chain counts also size the exchange exactly when
        # the chain filters rows.
        m = max(1, self.sample_size // max(1, blk.n_shards))
        samp_cap = blk.capacity  # plain int: samp_fn must not pin the Block

        def samp_fn(counts_arg, *col_arrays):
            cols, count = _apply_chain(
                chain, dict(zip(samp_in, col_arrays)), counts_arg[0]
            )
            keycols = ((cols[KEY], cols[lo_name]) if composite
                       else (cols[KEY],))
            stride = jnp.maximum(jnp.int32(1), count // jnp.int32(m))
            pos = jnp.clip(lax.iota(jnp.int32, 2 * m) * stride,
                           0, max(samp_cap - 1, 0))
            return (count.reshape(1),) + tuple(
                jnp.take(kc, pos).reshape(1, -1) for kc in keycols
            )

        samp_prog = _cached_program(
            ("sortsamp", self.mesh, m, blk.capacity, composite,
             tuple(samp_in), _chain_fp(chain)),
            lambda: _shard_program(
                self.mesh, samp_fn, 1 + len(samp_in),
                (_SPEC,) * (2 + composite),
            ),
        )
        samp_out = mesh_lib.host_get(
            samp_prog(blk.counts, *[blk.cols[nm] for nm in samp_in])
        )
        counts_host = np.asarray(samp_out[0]).reshape(-1)
        samp_hi = np.asarray(samp_out[1]).reshape(blk.n_shards, 2 * m)
        if composite:
            samp_lo = np.asarray(samp_out[2]).reshape(blk.n_shards, 2 * m)
        samples = []
        for s in range(blk.n_shards):
            c = int(counts_host[s])
            if c == 0:
                continue
            stride = max(1, c // m)
            n_valid = min(2 * m, -(-c // stride))
            keys = samp_hi[s, :n_valid]
            if composite:
                keys = block_lib.decode_i64(keys, samp_lo[s, :n_valid])
            samples.append(keys)
        if samples:
            allk = np.sort(np.concatenate(samples))
            if not self.ascending:
                allk = allk[::-1]
            idx = [int(len(allk) * i / n) for i in range(1, n)]
            bounds = allk[idx] if len(allk) else np.array([], allk.dtype)
        elif composite:
            bounds = np.zeros((n - 1,), np.int64)
        else:
            bounds = np.zeros((n - 1,),
                              np.dtype(dict(self.parent._schema())[KEY]))
        repl = mesh_lib.replicated_spec(self.mesh)
        if composite:
            bounds_hi, bounds_lo = block_lib.encode_i64(bounds)
            bounds_dev = mesh_lib.host_put(bounds_hi, repl)
            bounds_lo_dev = mesh_lib.host_put(bounds_lo, repl)
        else:
            bounds_dev = mesh_lib.host_put(bounds, repl)
            bounds_lo_dev = None
        ascending = self.ascending
        sort_impl = _sort_impl()

        def build(slot, out_cap):
            exchange, x_tok = self._resolve_exchange((blk,), slot, out_cap)

            def prog_fn(*args):
                if composite:
                    bnds, bnds_lo, counts, *col_arrays = args
                else:
                    (bnds, counts, *col_arrays), bnds_lo = args, None
                cols, count = _apply_chain(
                    chain, dict(zip(in_names, col_arrays)), counts[0]
                )
                keys = cols[KEY]
                if n == 1:
                    bucket = jnp.zeros_like(keys, shape=keys.shape).astype(jnp.int32)
                else:
                    bucket = kernels.range_bucket(
                        bnds, keys, ascending, bounds_lo=bnds_lo,
                        keys_lo=cols.get(lo_name) if composite else None,
                    )
                cols, count, overflow = exchange(
                    cols, count, bucket, n, slot, out_cap,
                    sort_impl=sort_impl,
                )
                cols = kernels.sort_by_column(
                    cols, count, KEY, descending=not ascending,
                    lo_name=lo_name, impl=sort_impl,
                )
                return (count.reshape(1),) + tuple(
                    cols[nm] for nm in names
                ) + (overflow.reshape(1),)

            key = ("sort", self.mesh, tuple(in_names), tuple(names),
                   _chain_fp(chain), n, slot, out_cap,
                   ascending, self.exchange_mode, x_tok, sort_impl)
            prog = _cached_program(
                key,
                lambda: _shard_program(
                    self.mesh, prog_fn,
                    (_REPL,) * (1 + composite)
                    + (_SPEC,) * (1 + len(in_names)),
                    (_SPEC,) * (2 + len(names)),
                ),
            )
            dev_bounds = ((bounds_dev, bounds_lo_dev) if composite
                          else (bounds_dev,))
            return prog, (*dev_bounds, blk.counts,
                          *[blk.cols[nm] for nm in in_names])

        outs, out_cap = self._run_exchange(
            build, counts_host,
            make_hists=lambda: ([self._range_histogram(
                blk, bounds_dev, ascending, bounds_lo_dev,
                chain=chain)], None),
            # Bounds are data-derived: same data -> same bounds, and a
            # changed distribution changes the bounds, so they belong in
            # the hint identity (with the post-chain counts the sampling
            # already fetched).
            hint_key=self._hint_key(counts_host.tobytes(),
                                    bounds.tobytes()),
        )
        counts, col_arrays = outs[0], outs[1:]
        return self._attach_pending(Block(
            cols=dict(zip(names, col_arrays)), counts=counts,
            capacity=out_cap, mesh=self.mesh,
            counts_host=self._last_counts_host))


class _CartesianDenseRDD(DenseRDD):
    """Device cross product: right side replicated, each shard
    ragged-expands its left rows against all right rows (m = rtotal per
    valid left row -> ragged_expand slot ownership). Parents materialize
    at construction: the product-size budget gate needs real counts, and
    an over-budget product must fall back to the host tier's lazy
    cartesian BEFORE a node type is fixed."""

    def __init__(self, left: DenseRDD, right: DenseRDD, budget: int):
        lblk = left.block()
        rblk = right.block()
        r_total = rblk.num_rows
        l_counts = lblk.counts_np
        max_l = int(l_counts.max()) if l_counts.size else 0
        out_cap = block_lib._round_capacity(max(max_l * max(r_total, 1), 1))
        row_bytes = sum(c.dtype.itemsize for c in lblk.cols.values()) + \
            sum(c.dtype.itemsize for c in rblk.cols.values())
        if out_cap * row_bytes * 3 > budget:
            raise _NotTraceable(
                f"cartesian product (~{out_cap} rows/shard) exceeds the "
                "HBM budget — host tier streams it lazily instead"
            )
        super().__init__(left.context, left.mesh, [left, right])
        self.left = left
        self.right = right
        self._r_total = r_total
        self._out_cap = out_cap

    def _schema(self):
        # Canonical (KEY, VALUE) so the product is a pair RDD on BOTH
        # tiers: host cartesian's (x, y) tuples are pairs, and the dense
        # result must accept the same downstream pair ops.
        ldt = dict(self.left._schema())[VALUE]
        rdt = dict(self.right._schema())[VALUE]
        return ((KEY, ldt), (VALUE, rdt))

    def _materialize(self) -> Block:
        lblk = self.left.block()
        rblk = self.right.block()
        n = self.mesh.size
        r_total, out_cap = self._r_total, self._out_cap
        if r_total == 0:
            # Empty right side: the product is empty; build it directly
            # (a zero-length replicated operand cannot be gathered from).
            schema = dict(self._schema())
            return block_lib.from_numpy(
                {KEY: np.zeros(0, schema[KEY]),
                 VALUE: np.zeros(0, schema[VALUE])},
                self.mesh,
            )
        rvals_host = rblk.to_numpy()[VALUE]
        rvals = mesh_lib.host_put(rvals_host,
                               mesh_lib.replicated_spec(self.mesh))

        def prog_fn(rv, counts, lvals):
            cap = lvals.shape[0]
            m = jnp.where(kernels.valid_mask(cap, counts[0]),
                          jnp.int32(r_total), 0)
            owner, off, total = kernels.ragged_expand(m, out_cap)
            a = jnp.take(lvals, owner)
            b = jnp.take(rv, jnp.clip(off, 0, max(r_total - 1, 0)))
            return total.reshape(1), a, b

        prog = _cached_program(
            ("cart", self.mesh, n, lblk.capacity, r_total, out_cap),
            lambda: _shard_program(self.mesh, prog_fn,
                                   (_REPL, _SPEC, _SPEC), (_SPEC,) * 3),
        )
        counts, a, b = prog(rvals, lblk.counts, lblk.cols[VALUE])
        return Block(cols={KEY: a, VALUE: b}, counts=counts,
                     capacity=out_cap, mesh=self.mesh)


class _SampleRDD(_NarrowRDD):
    """Per-shard Bernoulli sampling with a threefry stream folded by shard id
    (deterministic per (seed, shard))."""

    def __init__(self, parent: DenseRDD, fraction: float, seed: int):
        super().__init__(parent, parent._schema())
        self._fraction = float(fraction)
        self._seed = int(seed)
        self._user_fn = ("sample", self._fraction, self._seed)

    def _shard_fn(self, cols, count):
        cap = next(iter(cols.values())).shape[0]
        # Per-shard stream: fold the shard's first-row global position in.
        shard_tag = count * 0 + lax.axis_index(mesh_lib.SHARD_AXIS)
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed), shard_tag)
        u = jax.random.uniform(key, (cap,))
        keep = (u < self._fraction) & kernels.valid_mask(cap, count)
        return kernels.compact(cols, keep, cap)


def _grouped_columnar(keys: np.ndarray, vals: np.ndarray):
    """(group_keys, offsets, values) from key-sorted runs: group i's values
    are values[offsets[i]:offsets[i+1]]. Pure vectorized numpy — no per-row
    or per-key Python. Rows from different shards never share a key (hash
    partitioning), so a key change marks every group boundary including
    shard boundaries."""
    if len(keys) == 0:
        return keys, np.zeros(1, dtype=np.int64), vals
    starts = np.concatenate(
        [[0], np.flatnonzero(keys[1:] != keys[:-1]) + 1]
    ).astype(np.int64)
    offsets = np.concatenate([starts, [len(keys)]])
    return keys[starts], offsets, vals


def _sorted_runs(keys: np.ndarray, vals: np.ndarray):
    """(key, [values]) pairs from a key-sorted run (shared by group_by_key
    collect/compute and cogroup) — the host-facing view of
    _grouped_columnar; per-GROUP (not per-row) Python cost."""
    group_keys, offsets, values = _grouped_columnar(keys, vals)
    for i, k in enumerate(group_keys.tolist()):
        yield k, values[offsets[i]:offsets[i + 1]].tolist()


class _DenseCoGroupRDD(RDD):
    """Host-facing view over two device-grouped blocks: each side runs the
    dense group-by-key exchange (same hash -> same shard), and compute()
    merges the two sorted runs per shard into (k, (l_values, r_values)).

    Because this is a plain RDD with a partitioner-consistent layout, every
    host pair op (join variants, flat_map_values, ...) composes on top."""

    def __init__(self, left: DenseRDD, right: DenseRDD):
        from vega_tpu.dependency import OneToOneDependency

        self.left_grouped = _GroupByKeyRDD(left)
        self.right_grouped = _GroupByKeyRDD(right)
        super().__init__(left.context, deps=[
            OneToOneDependency(self.left_grouped),
            OneToOneDependency(self.right_grouped),
        ])
        self.mesh = left.mesh

    @property
    def num_partitions(self) -> int:
        return self.mesh.size

    def compute(self, split: Split, task_context=None):
        # Columnar alignment: both sides are key-sorted runs, so the merge
        # is two vectorized searchsorted passes; Python cost is per GROUP
        # (the unavoidable host-facing (k, ([lvs], [rvs])) assembly), never
        # per row.
        lrows = self.left_grouped.block().shard_rows(split.index)
        rrows = self.right_grouped.block().shard_rows(split.index)
        lk, loff, lv = _grouped_columnar(lrows[KEY], lrows[VALUE])
        rk, roff, rv = _grouped_columnar(rrows[KEY], rrows[VALUE])

        union = np.union1d(lk, rk)
        li = np.searchsorted(lk, union)
        ri = np.searchsorted(rk, union)
        has_l = np.isin(union, lk, assume_unique=True)
        has_r = np.isin(union, rk, assume_unique=True)
        for j, k in enumerate(union.tolist()):
            lvs = (lv[loff[li[j]]:loff[li[j] + 1]].tolist()
                   if has_l[j] else [])
            rvs = (rv[roff[ri[j]]:roff[ri[j] + 1]].tolist()
                   if has_r[j] else [])
            yield (k, (lvs, rvs))

    def collect(self) -> list:
        out = []
        for s in range(self.num_partitions):
            out.extend(self.compute(Split(s)))
        return out

    def collect_grouped(self):
        """Columnar cogroup: (keys, l_offsets, l_values, r_offsets,
        r_values) — group i's left values are
        l_values[l_offsets[i]:l_offsets[i+1]] (resp. right). No per-row or
        per-key Python: keys are hash-disjoint across shards and sorted
        within one, so each shard's two sides align with one union +
        searchsorted pass and value arrays concatenate untouched."""
        def expand_offsets(gk, goff, union):
            # gk is a subset of the sorted union, so one scatter places
            # each group's length at its union slot.
            lengths = np.zeros(len(union), dtype=np.int64)
            lengths[np.searchsorted(union, gk)] = goff[1:] - goff[:-1]
            return np.concatenate([[0], np.cumsum(lengths)])

        # One device gather per side (counts fetched once, columns whole);
        # shard boundaries are then host-side splits — no per-shard
        # device round-trips.
        lblk = self.left_grouped.block()
        rblk = self.right_grouped.block()
        l_counts = lblk.counts_np
        r_counts = rblk.counts_np
        lall = lblk.to_numpy()
        rall = rblk.to_numpy()

        def shard_parts(all_cols, counts):
            splits = np.cumsum(counts)[:-1]
            return (np.split(all_cols[KEY], splits),
                    np.split(all_cols[VALUE], splits))

        lk_s, lv_s = shard_parts(lall, l_counts)
        rk_s, rv_s = shard_parts(rall, r_counts)

        keys_parts, lv_parts, rv_parts = [], [], []
        lo_parts, ro_parts = [np.zeros(1, np.int64)], [np.zeros(1, np.int64)]
        l_base = r_base = 0
        for s in range(self.num_partitions):
            lk, loff, lv = _grouped_columnar(lk_s[s], lv_s[s])
            rk, roff, rv = _grouped_columnar(rk_s[s], rv_s[s])
            union = np.union1d(lk, rk)
            if not len(union):
                continue
            keys_parts.append(union)
            lo = expand_offsets(lk, loff, union)
            ro = expand_offsets(rk, roff, union)
            lo_parts.append(lo[1:] + l_base)
            ro_parts.append(ro[1:] + r_base)
            l_base += lo[-1]
            r_base += ro[-1]
            lv_parts.append(lv)
            rv_parts.append(rv)
        if not keys_parts:
            zero = np.zeros(1, np.int64)
            return (lall[KEY][:0], zero, lall[VALUE][:0],
                    zero, rall[VALUE][:0])
        return (np.concatenate(keys_parts),
                np.concatenate(lo_parts), np.concatenate(lv_parts),
                np.concatenate(ro_parts), np.concatenate(rv_parts))


class _DenseUnionRDD(DenseRDD):
    """Per-shard concatenation of two same-schema dense RDDs."""

    def __init__(self, first: DenseRDD, second: DenseRDD):
        super().__init__(first.context, first.mesh, [first, second])
        self.first = first
        self.second = second

    @property
    def hash_placed(self) -> bool:
        # Same placement function on both sides -> concat preserves it.
        return self.first.hash_placed and self.second.hash_placed

    def _settle_placement(self) -> None:
        self.first._settle_placement()
        self.second._settle_placement()

    def _schema(self):
        return self.first._schema()

    def _materialize(self) -> Block:
        a = self.first.block()
        b = self.second.block()
        names = [n for n, _ in self._schema()]
        concat_cap = a.capacity + b.capacity
        # Size the output from VALID counts when both sides already know
        # them on host (block() settled them; no fetch here, ever) —
        # capacity-sum sizing made the streamed reduce's accumulator
        # union grow its capacity geometrically: each chunk's elided
        # merge inherited cap(acc)+cap(partial), so the accumulator
        # DOUBLED per chunk at constant key count (16->32->64->128 MiB
        # at 1M keys; round-5 stream_1b profiling). Known counts also
        # ride out on the Block so downstream elided exchanges
        # (_elide_out_cap) size tightly instead of falling back to
        # capacity.
        counts_host = None
        if a.counts_host is not None and b.counts_host is not None:
            counts_host = (np.asarray(a.counts_host)
                           + np.asarray(b.counts_host))
            out_cap = block_lib._round_capacity(
                max(int(counts_host.max()), 1))
        else:
            out_cap = block_lib._round_capacity(concat_cap)
        cap_a = a.capacity  # plain int: the closure must not pin the Block

        def shard_concat(ac, bc, *cols):
            half = len(names)
            a_cols = dict(zip(names, cols[:half]))
            b_cols = dict(zip(names, cols[half:]))
            a_count, b_count = ac[0], bc[0]
            # Concatenate at full width, then compact into the (possibly
            # smaller, counts-sized) output capacity.
            out = {name: jnp.concatenate([a_cols[name], b_cols[name]])
                   for name in names}
            # mark validity: rows [0,a_count) and [cap_a, cap_a+b_count)
            idx = lax.iota(jnp.int32, concat_cap)
            keep = (idx < a_count) | (
                (idx >= cap_a) & (idx < cap_a + b_count)
            )
            return kernels.compact(out, keep, out_cap) + tuple()

        def prog_fn(ac, bc, *cols):
            out, count = shard_concat(ac, bc, *cols)
            return (count.reshape(1),) + tuple(out[n] for n in names)

        prog = _cached_program(
            ("dense_union", self.mesh, tuple(names), a.capacity, b.capacity,
             out_cap),
            lambda: _shard_program(
                self.mesh, prog_fn, 2 + 2 * len(names),
                (_SPEC,) * (1 + len(names)),
            ),
        )
        outs = prog(a.counts, b.counts,
                    *[a.cols[n] for n in names], *[b.cols[n] for n in names])
        counts, col_arrays = outs[0], outs[1:]
        return Block(cols=dict(zip(names, col_arrays)), counts=counts,
                     capacity=out_cap, mesh=self.mesh,
                     counts_host=counts_host)


def _infer_named_op(func) -> Optional[str]:
    """Sound monoid recognition shared with the host tier (exact identities
    only — see vega_tpu/rdd/pair.py:_infer_named_op). Unrecognized
    associative functions still run correctly via the segmented
    associative-scan path; this only selects the faster XLA segment op."""
    from vega_tpu.rdd.pair import _infer_named_op as _host_infer

    return _host_infer(func)
