"""Pallas TPU kernels for the dense tier's hot scalar ops.

The exchange pipeline's non-sort cost is hashing + bucketing every key
(tpu/kernels.py hash32). XLA fuses these elementwise ops well, but routing
them through Pallas keeps the whole hash+bucket step in one VMEM-resident
kernel (no intermediate HBM round trips between the four mixer stages) and
establishes the kernel plumbing richer kernels can extend.

Kernels run compiled on TPU and in interpreter mode elsewhere (tests run
interpret=True on CPU). All shapes are padded to the (8, 128) f32/i32 tile
internally; callers see flat arrays.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES


def _hash_bucket_kernel(keys_ref, out_ref, *, n_buckets: int):
    """lowbias32 finalizer + modulo bucketing, one VMEM block at a time."""
    x = keys_ref[:].astype(jnp.uint32)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> jnp.uint32(16))
    out_ref[:] = (x % jnp.uint32(n_buckets)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(1, 2))
def hash_bucket_pallas(keys: jax.Array, n_buckets: int,
                       interpret: bool = False) -> jax.Array:
    """bucket = lowbias32(key) % n_buckets via one Pallas kernel.

    Bit-identical to kernels.hash32(...) % n_buckets for int32 keys (the
    device-tier bucketing contract)."""
    n = keys.shape[0]
    padded = -(-n // _TILE) * _TILE
    grid = padded // _TILE
    keys2d = jnp.pad(keys, (0, padded - n)).reshape(-1, _LANES)

    out = pl.pallas_call(
        functools.partial(_hash_bucket_kernel, n_buckets=n_buckets),
        out_shape=jax.ShapeDtypeStruct(keys2d.shape, jnp.int32),
        grid=(grid,),
        # index_map yields BLOCK indices (block i covers rows
        # [i*_SUBLANES, (i+1)*_SUBLANES) of the 2D view).
        in_specs=[pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(keys2d)
    return out.reshape(-1)[:n]


def hash_bucket(keys: jax.Array, n_buckets: int) -> jax.Array:
    """Platform-dispatched bucketing: Pallas on TPU, plain XLA elsewhere
    (pallas interpret mode is for tests, not production CPU)."""
    from vega_tpu.tpu import kernels

    if keys.dtype == jnp.int32 and jax.default_backend() == "tpu":
        return hash_bucket_pallas(keys, n_buckets)
    return (kernels.hash32(keys) % jnp.uint32(n_buckets)).astype(jnp.int32)
