"""Pallas TPU kernels for the dense tier's hot scalar ops.

The exchange pipeline's non-sort cost is hashing + bucketing every key
(tpu/kernels.py hash32). XLA fuses these elementwise ops well, but routing
them through Pallas keeps the whole hash+bucket step in one VMEM-resident
kernel (no intermediate HBM round trips between the four mixer stages) and
establishes the kernel plumbing richer kernels can extend.

Kernels run compiled on TPU and in interpreter mode elsewhere (tests run
interpret=True on CPU). All shapes are padded to the (8, 128) f32/i32 tile
internally; callers see flat arrays.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vega_tpu.tpu import compat

_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES


def _hash_bucket_kernel(keys_ref, out_ref, *, n_buckets: int):
    """lowbias32 finalizer + modulo bucketing, one VMEM block at a time."""
    x = keys_ref[:].astype(jnp.uint32)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> jnp.uint32(16))
    out_ref[:] = (x % jnp.uint32(n_buckets)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(1, 2))
def hash_bucket_pallas(keys: jax.Array, n_buckets: int,
                       interpret: bool = False) -> jax.Array:
    """bucket = lowbias32(key) % n_buckets via one Pallas kernel.

    Bit-identical to kernels.hash32(...) % n_buckets for int32 keys (the
    device-tier bucketing contract)."""
    n = keys.shape[0]
    padded = -(-n // _TILE) * _TILE
    grid = padded // _TILE
    keys2d = jnp.pad(keys, (0, padded - n)).reshape(-1, _LANES)

    out = pl.pallas_call(
        functools.partial(_hash_bucket_kernel, n_buckets=n_buckets),
        out_shape=jax.ShapeDtypeStruct(keys2d.shape, jnp.int32),
        grid=(grid,),
        # index_map yields BLOCK indices (block i covers rows
        # [i*_SUBLANES, (i+1)*_SUBLANES) of the 2D view).
        in_specs=[pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(keys2d)
    return out.reshape(-1)[:n]


def hash_bucket(keys: jax.Array, n_buckets: int) -> jax.Array:
    """Platform-dispatched bucketing: Pallas on TPU, plain XLA elsewhere
    (pallas interpret mode is for tests, not production CPU)."""
    from vega_tpu.tpu import kernels

    if keys.dtype == jnp.int32 and jax.default_backend() == "tpu":
        return hash_bucket_pallas(keys, n_buckets)
    return (kernels.hash32(keys) % jnp.uint32(n_buckets)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# counting-partition rank kernel
# ---------------------------------------------------------------------------
#
# The stable counting partition (kernels._group_by_bucket, and through it
# partition_by_bucket / the sort_partition reduce plan) needs, per row,
# pos = starts[bucket] + (# earlier rows with the same bucket). The XLA
# formulation materializes a [capacity, n_buckets+1] one-hot plus its
# column cumsum in HBM — O(capacity * k) reads+writes. This kernel streams
# the bucket column ONCE: per (8, 128) VMEM tile it computes in-tile
# exclusive ranks with 2D cumsums (statically unrolled over the small
# bucket range) and carries per-bucket running totals across the
# sequential grid in a VMEM scratch — O(capacity) HBM traffic total.


def _partition_pos_kernel(starts_ref, bucket_ref, pos_ref, carry_ref,
                          *, n_bins: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for bb in range(n_bins):  # SMEM takes scalar stores only
            carry_ref[0, bb] = 0

    b = bucket_ref[:]  # (8, 128) int32, values in [0, n_bins)
    pos = jnp.zeros_like(b)
    # Mosaic has no cumsum primitive: exclusive prefix sums become
    # triangular matmuls (exact in f32 — tile counts are < 2^24).
    lane = jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 0)
    lane_t = jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 1)
    upper = (lane < lane_t).astype(jnp.float32)  # strict: exclusive
    sub = jax.lax.broadcasted_iota(jnp.int32, (_SUBLANES, _SUBLANES), 0)
    sub_t = jax.lax.broadcasted_iota(jnp.int32, (_SUBLANES, _SUBLANES), 1)
    lower = (sub_t < sub).astype(jnp.float32)
    for bb in range(n_bins):  # static unroll: n_bins small (mesh size + 1)
        m = (b == bb).astype(jnp.float32)
        # exclusive prefix count in row-major tile order: within-sublane
        # prefix + whole-earlier-sublane totals
        cs_l = jnp.dot(m, upper, preferred_element_type=jnp.float32)
        row_tot = jnp.sum(m, axis=1, keepdims=True)  # (8, 1)
        cs_s = jnp.dot(lower, row_tot,
                       preferred_element_type=jnp.float32)
        excl = (cs_l + cs_s).astype(jnp.int32)
        base = starts_ref[0, bb] + carry_ref[0, bb]
        sel = m.astype(jnp.int32)
        pos = pos + sel * (base + excl)
        carry_ref[0, bb] = carry_ref[0, bb] + \
            jnp.sum(m).astype(jnp.int32)
    pos_ref[:] = pos


@functools.partial(jax.jit, static_argnums=(1, 3))
def partition_pos_pallas(bucket: jax.Array, n_bins: int,
                         starts: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """pos[i] = starts[bucket[i]] + |{j < i : bucket[j] == bucket[i]}|.

    bucket values must lie in [0, n_bins) (callers pass n_shards + 1 bins:
    real buckets plus the ghost). starts is int32[n_bins] (exclusive
    prefix of the per-bucket totals). Bit-identical to the XLA one-hot
    rank path in kernels._group_by_bucket."""
    n = bucket.shape[0]
    padded = -(-n // _TILE) * _TILE
    grid = padded // _TILE
    # padding rows use bucket n_bins-1 (the ghost bin): they come after
    # every real row, so real positions are unaffected; their pos values
    # are sliced off below.
    b2d = jnp.pad(bucket, (0, padded - n),
                  constant_values=n_bins - 1).reshape(-1, _LANES)
    starts_pad = -(-n_bins // _LANES) * _LANES
    starts2d = jnp.pad(starts.astype(jnp.int32),
                       (0, starts_pad - n_bins)).reshape(1, -1)

    out = pl.pallas_call(
        functools.partial(_partition_pos_kernel, n_bins=n_bins),
        out_shape=jax.ShapeDtypeStruct(b2d.shape, jnp.int32),
        grid=(grid,),
        in_specs=[
            # per-bucket scalars live in SMEM: the kernel reads/writes
            # them one element at a time (VMEM refuses scalar stores)
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        scratch_shapes=[pltpu.SMEM((1, starts_pad), jnp.int32)],
        interpret=interpret,
    )(starts2d, b2d)
    return out.reshape(-1)[:n]


def _digit_hist_kernel(d_ref, hist_ref, *, n_bins: int):
    """Accumulate per-bin counts across the sequential grid. hist lives
    in SMEM (scalar stores only)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for bb in range(n_bins):
            hist_ref[0, bb] = 0

    b = d_ref[:]
    for bb in range(n_bins):
        hist_ref[0, bb] += jnp.sum((b == bb).astype(jnp.int32))


@functools.partial(jax.jit, static_argnums=(1, 2))
def digit_hist_pallas(digits: jax.Array, n_bins: int,
                      interpret: bool = False) -> jax.Array:
    """Histogram of small-range int32 digits in one streaming pass
    (per-tile counts accumulated in SMEM) — no [n, n_bins] one-hot in
    HBM. Padding rows land in bin n_bins-1; the caller's use (exclusive
    prefix starts) never reads that bin's count downstream of real rows
    in lower bins."""
    n = digits.shape[0]
    padded = -(-n // _TILE) * _TILE
    grid = padded // _TILE
    d2d = jnp.pad(digits, (0, padded - n),
                  constant_values=n_bins - 1).reshape(-1, _LANES)
    pad_bins = -(-n_bins // _LANES) * _LANES

    out = pl.pallas_call(
        functools.partial(_digit_hist_kernel, n_bins=n_bins),
        out_shape=jax.ShapeDtypeStruct((1, pad_bins), jnp.int32),
        grid=(grid,),
        in_specs=[pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=interpret,
    )(d2d)
    hist = out.reshape(-1)[:n_bins]
    # un-count the padding rows from the top bin
    return hist.at[n_bins - 1].add(-(padded - n))


def _xla_onehot_pos(bucket: jax.Array, starts: jax.Array,
                    n_bins: int) -> jax.Array:
    """XLA rank path: [n, n_bins] one-hot + column cumsum (O(n * n_bins)
    HBM intermediates)."""
    one_hot = (bucket[:, None] ==
               jnp.arange(n_bins)[None, :]).astype(jnp.int32)
    rank = jnp.take_along_axis(
        jnp.cumsum(one_hot, axis=0), bucket[:, None], axis=1)[:, 0] - 1
    return jnp.take(starts, bucket) + rank


def _xla_argsort_pos(bucket: jax.Array, starts: jax.Array,
                     n_bins: int) -> jax.Array:
    """XLA low-memory rank path: positions from a stable argsort
    (O(n log n) time, O(n) memory — no one-hot intermediates)."""
    del starts  # the sorted order already encodes starts+rank
    n = bucket.shape[0]
    order = jnp.argsort(bucket, stable=True)
    return jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))


def bucket_hist(bucket: jax.Array, n_bins: int) -> jax.Array:
    """Per-bucket counts (bincount replacement for small bucket ranges),
    platform-selected at lowering: the Pallas streaming histogram on TPU
    (jnp.bincount lowers to scatter-adds there), bincount elsewhere.
    Large ranges keep bincount everywhere — the kernel statically
    unrolls a per-bin step, same bound as the rank kernel's gate."""
    if n_bins > 65:
        return jnp.bincount(bucket, length=n_bins).astype(jnp.int32)
    return compat.platform_dependent(
        bucket,
        tpu=lambda b: digit_hist_pallas(b, n_bins),
        default=lambda b: jnp.bincount(b, length=n_bins).astype(jnp.int32),
    )


def radix_hist(digits: jax.Array, n_bins: int = 256) -> jax.Array:
    """Digit histogram for one radix pass, platform-selected at lowering:
    the Pallas streaming kernel on TPU, bincount elsewhere. n_bins = 2^bits
    (8-bit digits -> fewer passes, 4-bit -> 16x less per-tile unroll; the
    hardware A/B decides)."""
    return compat.platform_dependent(
        digits,
        tpu=lambda d: digit_hist_pallas(d, n_bins),
        default=lambda d: jnp.bincount(d, length=n_bins).astype(jnp.int32),
    )


def radix_pos(digits: jax.Array, starts: jax.Array,
              n_bins: int = 256) -> jax.Array:
    """Stable counting-partition positions for one radix pass,
    platform-selected at lowering (Pallas rank kernel on TPU)."""
    return compat.platform_dependent(
        digits, starts,
        tpu=lambda d, s: partition_pos_pallas(d, n_bins, s),
        default=lambda d, s: _xla_onehot_pos(d, s, n_bins),
    )


def partition_pos(bucket: jax.Array, n_bins: int, starts: jax.Array,
                  prefer_low_memory: bool = False):
    """Partition ranks pos[i] = starts[bucket[i]] + earlier-equal count,
    platform-selected AT LOWERING TIME (lax.platform_dependent): tpu gets
    the Pallas kernel — so a program exported with platforms=["tpu"]
    carries the Mosaic kernel and the offline lowering tier validates the
    REAL composed TPU program — other platforms get the XLA one-hot path,
    or the argsort path under prefer_low_memory (on TPU the Pallas kernel
    already streams in O(n), so the flag only shapes the fallback).
    Returns None when the kernel can't apply (caller keeps its own path)."""
    if n_bins > 65 or bucket.dtype != jnp.int32:
        return None
    fallback = _xla_argsort_pos if prefer_low_memory else _xla_onehot_pos
    return compat.platform_dependent(
        bucket, starts,
        tpu=lambda b, s: partition_pos_pallas(b, n_bins, s),
        default=lambda b, s: fallback(b, s, n_bins),
    )
