"""Streamed dense sources: datasets bigger than the HBM budget.

The dense tier materializes whole Blocks; a 1B-row (key, value) source is
~8 GB of raw columns and several times that in transient exchange buffers
— it cannot live resident on one chip (SURVEY.md §7 hard part 6; the
reference never solved memory either: cache.rs:68-76 eviction is todo!()).

A StreamedDenseRDD holds a *recipe* for the data as a sequence of chunk
DenseRDDs, each small enough that its planned exchange footprint fits
Configuration.dense_hbm_budget (the exchange planner's per-chunk peak
estimate under dense_exchange=auto; the conservative
chunk_bytes * _EXCHANGE_FOOTPRINT rule otherwise) to run the normal
fused device pipelines.
Narrow ops (map/filter/map_values) compose per chunk. Aggregations stream:

  reduce_by_key: each chunk runs the full device exchange+segment-reduce,
  producing a small combiner block; partials fold into an accumulator via
  union + re-reduce (the accumulator is bounded by the number of distinct
  keys, not rows). The result is a REGULAR DenseRDD — downstream joins,
  sorts, collects run the resident path. This is the multi-pass schedule
  for BASELINE config 5's 1B-row group_by+join on a single chip.

  count/sum/min/max: per-chunk named reductions folded on the host.

Anything else — untraceable closures, group_by_key, collect, the whole
host-RDD surface — transparently falls back to the RESIDENT build (the
exact behavior auto-streaming replaced), preserving the two-tier contract
that unsupported operations degrade, never error. At scales where resident
materialization is impossible the fallback fails the same way it always
would; the streamed fast paths are how those scales are meant to run.

Chunking policy lives in planned_chunk_rows(): sources auto-stream when
their estimated block bytes exceed the budget; chunk_rows can be forced
explicitly.
"""

from __future__ import annotations

import logging
from typing import Callable, Iterator, Optional

import numpy as np

from vega_tpu.errors import VegaError

log = logging.getLogger("vega_tpu")

# Conservative fallback when no exchange plan is available (explicit
# dense_exchange={all_to_all,ring,staged} runs, or callers without a mesh
# in hand): a one-shot exchange holds ~this many transient copies of its
# operand block (operand + multi-key-sorted copy + send slots + received
# block), so a chunk is sized such that chunk_bytes * footprint <= budget.
# Under the default dense_exchange=auto, the collective-aware planner
# (tpu/exchange_plan.planned_stream_rows) replaces this constant with a
# per-exchange estimate — bounded (staged/ring) plans cap the transients,
# so chunks grow toward the operand+copy+output floor and the streamed
# multi-pass fold pays fewer passes.
_EXCHANGE_FOOTPRINT = 6


def _legacy_chunk_rows(n_rows: int, bytes_per_row: int,
                       budget_bytes: int) -> Optional[int]:
    if n_rows * bytes_per_row * _EXCHANGE_FOOTPRINT <= budget_bytes:
        return None
    return max(int(budget_bytes // (bytes_per_row * _EXCHANGE_FOOTPRINT)), 1)


def planned_chunk_rows(n_rows: int, bytes_per_row: int,
                       budget_bytes: int,
                       chunk_rows: Optional[int] = None,
                       n_shards: Optional[int] = None) -> Optional[int]:
    """None when the whole source fits the budget (no streaming needed),
    else the chunk size, rounded DOWN to a shape-stable bucket (1M-row
    multiples, or a power of two below 1M) so the chunk footprint stays
    within budget and block capacities repeat across chunks.

    With n_shards given and dense_exchange=auto (the default), the chunk
    is sized by the exchange planner's cost model instead of the fixed
    footprint constant: the largest chunk whose PLANNED exchange keeps
    its aggregate estimated peak within the budget. Forced exchange
    modes and mesh-less callers keep the conservative 6x rule."""
    if chunk_rows is not None:
        if int(chunk_rows) < 1:
            raise VegaError(f"chunk_rows must be >= 1, got {chunk_rows}")
        return int(chunk_rows)
    rows = None
    if n_shards is not None:
        from vega_tpu.env import Env

        if getattr(Env.get().conf, "dense_exchange", "auto") == "auto":
            from vega_tpu.tpu.exchange_plan import planned_stream_rows

            rows = planned_stream_rows(n_rows, bytes_per_row,
                                       budget_bytes, n_shards)
            if rows is None:
                return None
    if rows is None:
        rows = _legacy_chunk_rows(n_rows, bytes_per_row, budget_bytes)
        if rows is None:
            return None
    step = 1 << 20
    if rows >= step:
        return (rows // step) * step
    return max(128, 1 << (rows.bit_length() - 1))


class StreamedDenseRDD:
    """A chunked dense dataset: `chunks()` yields fresh per-chunk DenseRDDs
    (so HBM for one chunk is released before the next materializes), and
    `resident()` builds the equivalent un-chunked DenseRDD for operations
    that cannot stream.

    Not an RDD subclass on purpose: the host tier's per-partition pull
    model would force the whole dataset resident; the streamed surface is
    the explicit, bounded-memory subset of the dense API, with everything
    else delegated to the resident fallback."""

    def __init__(self, ctx, make_chunks: Callable[[], Iterator],
                 make_resident: Callable[[], object], n_chunks: int,
                 make_probe: Optional[Callable[[], object]] = None):
        self.context = ctx
        self._make_chunks = make_chunks
        self._make_resident = make_resident
        self.n_chunks = n_chunks
        # Tiny (few-row) chunk with the stream's schema, used only to
        # decide closure traceability — never full-size data.
        self._make_probe = make_probe or (
            lambda: next(iter(make_chunks()), None))
        self._resident_memo = None

    _INTERNALS = ("context", "n_chunks", "_make_chunks", "_make_resident",
                  "_make_probe", "_resident_memo")

    def resident(self):
        """The un-chunked DenseRDD this stream is a recipe for (or a host
        RDD, if a composed closure was untraceable). Memoized: repeated
        fallback ops materialize the dataset once, not per access."""
        if self._resident_memo is None:
            log.info(
                "streamed source: materializing resident build "
                "(%d chunks coalesce into one block)", self.n_chunks,
            )
            self._resident_memo = self._make_resident()
        return self._resident_memo

    def __getattr__(self, name):
        # Fallback surface: anything without a streaming implementation —
        # RDD internals included, so a streamed source captured as the
        # operand of a resident op (resident.join(streamed), union, ...)
        # behaves like its resident build inside host lineage. (Only
        # called for names not found normally; the _INTERNALS guard stops
        # recursion when instance attrs are probed before __init__ ran,
        # e.g. during unpickling.)
        if name in StreamedDenseRDD._INTERNALS:
            raise AttributeError(name)
        return getattr(self.resident(), name)

    # --- narrow ops: compose per chunk -----------------------------------
    def _per_chunk(self, op_name: str, apply) -> "StreamedDenseRDD":
        make = self._make_chunks
        make_probe = self._make_probe

        # Traceability probe on a few-row block BEFORE building the
        # streamed node: untraceable closures take the resident path
        # (which itself falls back to the host tier) instead of erroring
        # mid-stream. Node construction is lazy, so this allocates rows
        # only for the tiny probe block.
        probe = make_probe()
        if probe is not None:
            from vega_tpu.tpu.dense_rdd import DenseRDD

            if not isinstance(apply(probe), DenseRDD):
                log.info("streamed %s: closure not traceable — resident "
                         "fallback", op_name)
                return apply(self.resident())

        def chunks():
            for chunk in make():
                yield apply(chunk)

        # The child's resident build reuses the parent's memo, so sibling
        # fallbacks materialize the shared base once.
        return StreamedDenseRDD(self.context, chunks,
                                lambda: apply(self.resident()),
                                self.n_chunks,
                                make_probe=lambda: apply(make_probe()))

    def map(self, f: Callable):
        return self._per_chunk("map", lambda c: c.map(f))

    def filter(self, predicate: Callable):
        return self._per_chunk("filter", lambda c: c.filter(predicate))

    def map_values(self, f: Callable):
        return self._per_chunk("map_values", lambda c: c.map_values(f))

    def map_expand(self, f: Callable, factor: int):
        return self._per_chunk("map_expand",
                               lambda c: c.map_expand(f, factor))

    def flat_map_ragged(self, f: Callable, max_out_per_row: int):
        return self._per_chunk(
            "flat_map_ragged",
            lambda c: c.flat_map_ragged(f, max_out_per_row),
        )

    def join(self, other, partitioner_or_num=None, *,
             exchange: Optional[str] = None):
        """Streamed join against a RESIDENT right side: each chunk joins
        independently (a left row's matches depend only on the table), so
        the result streams too — a 1B-row enrichment join never
        materializes whole. The right side is hash-placed ONCE up front
        (one exchange+sort total; every per-chunk join then elides its
        side), and must itself fit the HBM budget — this is the
        broadcast-style enrichment join, not a stream-stream shuffle.
        A streamed right side is materialized resident first; non-dense
        right sides or explicit partitioners delegate to the resident
        build."""
        from vega_tpu.env import Env
        from vega_tpu.tpu.dense_rdd import DenseRDD, _GroupByKeyRDD

        if isinstance(other, StreamedDenseRDD):
            other = other.resident()
        if isinstance(other, DenseRDD) and partitioner_or_num is None:
            other._settle_placement()  # hash_placed reads are pure
            if not other.hash_placed:
                # One exchange+sort re-places the table; per-chunk joins
                # then skip the right side's exchange AND sort entirely.
                other = _GroupByKeyRDD(other)
            budget = getattr(Env.get().conf, "dense_hbm_budget", 4 << 30)
            blk = getattr(other, "_block", None)
            if blk is not None and blk.nbytes * 3 > budget:
                log.warning(
                    "streamed join: right side is %.1f MiB — chunk sizing "
                    "does not account for it; lower chunk_rows if HBM "
                    "overflows", blk.nbytes / 2**20,
                )
            return self._per_chunk(
                "join", lambda c: c.join(other, exchange=exchange)
            )
        return self.resident().join(other, partitioner_or_num)

    # --- streaming aggregations ------------------------------------------
    def reduce_by_key(self, func=None, partitioner_or_num=None, *,
                      op: Optional[str] = None,
                      exchange: Optional[str] = None):
        """Multi-pass reduce_by_key; returns a regular (resident) DenseRDD
        whose size is bounded by the number of distinct keys."""
        from vega_tpu.tpu.dense_rdd import (DenseRDD, _DenseUnionRDD,
                                            dense_from_block)

        # Traceability decided on the few-row probe BEFORE any chunk work:
        # an untraceable combiner degrades to the resident build's host
        # path without first burning a full chunk-sized host reduce.
        probe = self._make_probe()
        if probe is not None and not isinstance(
                probe.reduce_by_key(func, partitioner_or_num, op=op,
                                    exchange=exchange), DenseRDD):
            log.info("streamed reduce_by_key: combiner not traceable "
                     "— resident fallback")
            return self.resident().reduce_by_key(func, partitioner_or_num)

        acc = None
        for i, chunk in enumerate(self._make_chunks()):
            partial = chunk.reduce_by_key(func, partitioner_or_num, op=op,
                                          exchange=exchange)
            if not isinstance(partial, DenseRDD):
                # Belt-and-braces: the probe said traceable but a real
                # chunk disagreed (should not happen).
                log.info("streamed reduce_by_key: combiner not traceable "
                         "— resident fallback")
                return self.resident().reduce_by_key(
                    func, partitioner_or_num)
            merged = (partial if acc is None
                      else _DenseUnionRDD(acc, partial).reduce_by_key(
                          func, partitioner_or_num, op=op, exchange=exchange))
            # Materialize now and keep only the block: drops the lineage
            # references to this chunk's source so its HBM frees before the
            # next chunk builds. hash_placed comes from the MATERIALIZED
            # node, not assumed True: exchange outputs normally are (so
            # the per-chunk merge reduce elides, zero collectives), but a
            # wide-int64 overflow repair rebuilds via the host-exact fold
            # with no device placement — eliding over that block would
            # leave equal keys on different shards unmerged.
            blk = merged.block()
            acc = dense_from_block(self.context, blk,
                                   hash_placed=merged.hash_placed)
            log.info(
                "streamed reduce_by_key: chunk %d/%d -> %d keys "
                "(accumulator %.1f MiB device-resident)",
                i + 1, self.n_chunks, blk.num_rows, blk.nbytes / 2**20,
            )
        if acc is None:
            raise VegaError("streamed reduce_by_key on empty source")
        return acc

    def count(self) -> int:
        return sum(c.count() for c in self._make_chunks())

    def _fold_named(self, op: str):
        total = None
        for chunk in self._make_chunks():
            part = getattr(chunk, {"add": "sum", "min": "min",
                                   "max": "max"}[op])()
            if total is None:
                total = part
            elif op == "add":
                total = total + part
            elif op == "min":
                total = min(total, part)
            else:
                total = max(total, part)
        if total is None:
            raise VegaError("reduction over empty streamed source")
        return total

    def sum(self):
        return self._fold_named("add")

    def min(self):
        return self._fold_named("min")

    def max(self):
        return self._fold_named("max")

    def _stream_best(self, n: int, method: str, reverse: bool) -> list:
        best: list = []
        for chunk in self._make_chunks():
            best.extend(getattr(chunk, method)(n))
            best = sorted(best, reverse=reverse)[:n]
        return best

    def take_ordered(self, n: int, key=None) -> list:
        """Streamed order statistic (BASELINE config 5's take_ordered at
        1B rows): each chunk's device take_ordered yields <= n
        candidates; the driver keeps the running best n — the streamed
        analogue of the host tier's BoundedPriorityQueue merge
        (rdd.rs:1124-1153). Equivalent to sort_by_key().take_ordered(n)
        without materializing (or sorting) the full dataset. Custom key
        functions take the resident fallback like other closures."""
        if key is not None:
            return self.resident().take_ordered(n, key)
        return self._stream_best(n, "take_ordered", reverse=False)

    def top(self, n: int, key=None) -> list:
        if key is not None:
            return self.resident().top(n, key)
        return self._stream_best(n, "top", reverse=True)


def streamed_range(ctx, n: int, chunk_rows: int, mesh=None,
                   dtype=None) -> StreamedDenseRDD:
    """Chunked ctx.dense_range: chunk i covers [i*chunk_rows, ...)."""
    import jax.numpy as jnp

    from vega_tpu.tpu import block as block_lib
    from vega_tpu.tpu import mesh as mesh_lib
    from vega_tpu.tpu.dense_rdd import dense_from_block

    mesh = mesh or mesh_lib.default_mesh()
    dtype = dtype or jnp.int32
    n_chunks = -(-n // chunk_rows)

    def chunks():
        for i in range(n_chunks):
            start = i * chunk_rows
            size = min(chunk_rows, n - start)
            yield dense_from_block(
                ctx, block_lib.block_range(size, mesh, dtype, start=start)
            )

    def resident():
        return dense_from_block(ctx, block_lib.block_range(n, mesh, dtype))

    def probe():
        return dense_from_block(
            ctx, block_lib.block_range(min(n, 8), mesh, dtype)
        )

    return StreamedDenseRDD(ctx, chunks, resident, n_chunks,
                            make_probe=probe)


def streamed_npz(ctx, cols: dict, chunk_rows: int, mesh=None
                 ) -> StreamedDenseRDD:
    """Chunked dense_load_npz over already-loaded host columns: host RAM
    holds the file once (the caller's copy is reused, not re-read); HBM
    only ever holds one chunk."""
    from vega_tpu.tpu import block as block_lib
    from vega_tpu.tpu import mesh as mesh_lib
    from vega_tpu.tpu.dense_rdd import dense_from_block

    mesh = mesh or mesh_lib.default_mesh()
    # Encode int64 keys AND wide values AND string dictionaries ONCE over
    # the full column: per-chunk encoding would give chunks whose local
    # range fits int32 a different schema than chunks whose range
    # doesn't — and per-chunk dictionaries would make every accumulator
    # union pay a dictionary unification — the accumulator union needs
    # every chunk block to agree.
    from vega_tpu.tpu import dict_encoding

    cols, dicts = dict_encoding.encode_string_columns(dict(cols))
    cols = block_lib.encode_value_columns(
        block_lib.encode_key_columns(cols))
    n = len(next(iter(cols.values()))) if cols else 0
    n_chunks = max(1, -(-n // chunk_rows))

    def chunks():
        for i in range(n_chunks):
            lo = i * chunk_rows
            hi = min(lo + chunk_rows, n)
            yield dense_from_block(
                ctx,
                block_lib.from_numpy(
                    {name: col[lo:hi] for name, col in cols.items()}, mesh,
                    dicts=dicts,
                ),
            )

    def resident():
        return dense_from_block(
            ctx, block_lib.from_numpy(cols, mesh, dicts=dicts))

    def probe():
        if n == 0:
            return None
        tiny = {name: col[:min(n, 8)] for name, col in cols.items()}
        return dense_from_block(
            ctx, block_lib.from_numpy(tiny, mesh, dicts=dicts))

    return StreamedDenseRDD(ctx, chunks, resident, n_chunks,
                            make_probe=probe)
