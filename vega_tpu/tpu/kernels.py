"""Shard-local device algorithms for the dense tier.

These functions run *inside* jax.shard_map over the "shards" mesh axis: every
array is the per-shard view ([capacity, ...] columns, int32[1] count). They
replace the reference's shuffle planes with XLA-native equivalents
(SURVEY.md §7):

  reference map-side combine (dependency.rs:164-229)  -> bucket_by_hash + local segment pre-reduce
  HTTP pull shuffle (shuffle_manager.rs/shuffle_fetcher.rs) -> lax.all_to_all over ICI
  reduce-side merge (shuffled_rdd.rs:149-170)          -> sort + segment reduction
  cogroup/join merge (co_grouped_rdd.rs:206-249)       -> sort-merge join

Everything is static-shape: raggedness is (count, validity-mask), never a
dynamic dimension (SURVEY.md §7 hard part 1). Capacity overflow is detected
on device and surfaced as a flag the driver checks, then retries with a
larger capacity (the moral equivalent of MoE capacity-factor overflow).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from vega_tpu.tpu.mesh import SHARD_AXIS

Cols = Dict[str, jax.Array]

# ---------------------------------------------------------------------------
# hashing / masks / compaction
# ---------------------------------------------------------------------------


def hash32(col: jax.Array) -> jax.Array:
    """lowbias32 finalizer over a column's bit pattern (device analogue of
    partitioner.hash_key; 32-bit because TPUs have no native int64).

    Bucket placement need not match the host tier bit-for-bit — only final
    RDD *results* must match (BASELINE.md parity) — so the device tier uses
    the cheapest good mixer."""
    if col.dtype in (jnp.float32,):
        x = lax.bitcast_convert_type(col, jnp.uint32)
    elif col.dtype in (jnp.float64, jnp.int64, jnp.uint64):
        x64 = lax.bitcast_convert_type(col.astype(jnp.float64), jnp.uint64) \
            if jnp.issubdtype(col.dtype, jnp.floating) else col.astype(jnp.uint64)
        x = (x64 ^ (x64 >> jnp.uint64(32))).astype(jnp.uint32)
    else:
        x = col.astype(jnp.uint32)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> jnp.uint32(16))
    return x


def hash32_pair(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """Mix two 32-bit words into one 32-bit hash (the bucket hash for
    (hi, lo)-encoded int64 keys, block.py KEY_LO). hash-combine of the two
    lowbias32 digests followed by one more finalizer round; like hash32,
    only bucket placement depends on it, so any good mixer is valid."""
    a = hash32(hi)
    b = hash32(lo)
    x = a ^ (b + jnp.uint32(0x9E3779B9) + (a << jnp.uint32(6))
             + (a >> jnp.uint32(2)))
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    return x


def searchsorted2(rh: jax.Array, rl: jax.Array, qh: jax.Array,
                  ql: jax.Array, side: str = "left") -> jax.Array:
    """Vectorized lexicographic searchsorted over two-word keys: positions
    of queries (qh, ql) in rows (rh, rl) sorted by (rh major, rl minor).
    jnp.searchsorted cannot compare composite keys, so this is the classic
    branchless binary search unrolled to ceil(log2(n))+1 rounds — O(log n)
    vectorized gathers, no data-dependent control flow (jit-safe)."""
    n = rh.shape[0]
    lo = jnp.zeros(qh.shape, jnp.int32)
    hi = jnp.full(qh.shape, n, jnp.int32)
    for _ in range(max(1, int(n).bit_length())):
        mid = (lo + hi) >> 1
        safe = jnp.clip(mid, 0, max(n - 1, 0))
        mh = jnp.take(rh, safe)
        ml = jnp.take(rl, safe)
        if side == "left":
            go = (mh < qh) | ((mh == qh) & (ml < ql))
        else:
            go = (mh < qh) | ((mh == qh) & (ml <= ql))
        active = lo < hi
        lo = jnp.where(active & go, mid + 1, lo)
        hi = jnp.where(active & ~go, mid, hi)
    return lo


def valid_mask(capacity: int, count: jax.Array) -> jax.Array:
    return lax.iota(jnp.int32, capacity) < count


def compact(cols: Cols, keep: jax.Array, out_capacity: int) -> Tuple[Cols, jax.Array]:
    """Move rows where keep=True to the front; returns (cols, new_count).
    Stable (kept rows' positions are their exclusive prefix count, which is
    increasing), static-shape. Implemented as cumsum + scatter — O(n) work
    instead of the O(n log n) argsort this hot helper used to pay (it runs
    inside every exchange, filter, and segment reduction)."""
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    idx = jnp.where(keep, pos, out_capacity)  # dropped rows land out of range
    out = {}
    for n, c in cols.items():
        dst = jnp.zeros((out_capacity,) + c.shape[1:], c.dtype)
        out[n] = dst.at[idx].set(c, mode="drop")
    return out, jnp.sum(keep).astype(jnp.int32)


def gather_rows(cols: Cols, idx: jax.Array) -> Cols:
    return {n: jnp.take(c, idx, axis=0) for n, c in cols.items()}


# ---------------------------------------------------------------------------
# exchange: the device shuffle
# ---------------------------------------------------------------------------


def passthrough_exchange(cols: Cols, count: jax.Array, capacity: int,
                         out_capacity: int):
    """Single-shard fast path shared by every exchange implementation: the
    bucket/sort/collective is a no-op; just re-capacity the block."""
    mask = valid_mask(capacity, count)
    out, new_count = compact(cols, mask, out_capacity)
    return out, new_count, new_count > out_capacity


def _group_by_bucket(cols: Cols, bucket: jax.Array, n_shards: int,
                     prefer_low_memory: bool = False,
                     sort_impl: str = None):
    """Stable-group rows by target bucket; returns (grouped cols,
    per-bucket counts, per-bucket start offsets).

    Bucket ids live in the tiny range [0, n_shards] — for small meshes a
    counting sort (one-hot prefix counts + one scatter per column, O(n*k))
    beats the O(n log n) argsort. The one-hot/cumsum intermediates are
    O(capacity * n_shards), so callers with a memory bound to honor
    (ring_exchange) set prefer_low_memory and larger meshes always take the
    argsort path.

    sort_impl is the caller's RESOLVED dense_sort_impl — cached-program
    builders must thread the exact value that sits in their program-cache
    key (exchange/partition_by_bucket forward it), so an in-process config
    flip re-traces instead of silently A/B-ing a stale cached program.
    None (direct/uncached callers only) resolves from the live config."""
    from vega_tpu.tpu import pallas_kernels as _pk

    counts_all = _pk.bucket_hist(bucket, n_shards + 1)
    counts_to = counts_all[:n_shards]
    starts_all = jnp.cumsum(counts_all) - counts_all  # exclusive prefix
    starts = starts_all[:n_shards]
    if n_shards <= 64:
        from vega_tpu.tpu import pallas_kernels

        capacity = bucket.shape[0]
        # Platform-selected ranks (lax.platform_dependent): TPU streams
        # the bucket column once through the Pallas kernel (VMEM tile
        # ranks + SMEM per-bucket carries — O(capacity) HBM, so even
        # memory-bounded callers like ring_exchange use it); elsewhere
        # the XLA one-hot path, or the argsort path when
        # prefer_low_memory (the one-hot's O(capacity * n_shards)
        # intermediates are what that flag exists to avoid).
        pos = pallas_kernels.partition_pos(
            bucket, n_shards + 1, starts_all,
            prefer_low_memory=prefer_low_memory)
        if pos is not None:
            grouped = {}
            for name, col in cols.items():
                dst = jnp.zeros((capacity,) + col.shape[1:], col.dtype)
                grouped[name] = dst.at[pos].set(col, mode="drop")
            return grouped, counts_to, starts
    # Escape hatch (>64 buckets, or low-memory without the Pallas path).
    # Honors dense_sort_impl: 'packed' (and CPU 'auto') takes the
    # single-operand packed sort by bucket — same stable order as the
    # argsort at a fraction of the comparator cost; anything else keeps
    # the argsort so a pinned 'xla' (the unmeasured-on-chip-packed TPU
    # default) never executes packed code. Every row participates;
    # padding rows carry bucket == n_shards and sort last by value.
    if (sort_impl if sort_impl is not None
            else resolve_sort_impl()) == "packed":
        order = packed_sort_perm(orderable_words([bucket]),
                                 jnp.int32(bucket.shape[0]))
    else:
        order = jnp.argsort(bucket, stable=True)
    return gather_rows(cols, order), counts_to, starts


def bucket_key_sort(cols: Cols, count: jax.Array, bucket: jax.Array,
                    key_name: str, lo_name: str = None,
                    impl: str = "xla",
                    n_shards: int = None) -> Tuple[Cols, jax.Array]:
    """One stable multi-key sort by (bucket major, key minor).

    Rows become bucket-grouped with a key-sorted run per bucket, so a single
    lax.sort feeds BOTH the presorted map-side combine and a pregrouped
    exchange — replacing the separate pre-combine key sort and the
    exchange's bucket grouping (the 3-sorts-to-2 restructuring of the
    reference's map-side combine, dependency.rs:176-223). Caller must have
    ghosted invalid rows (bucket = n_shards) so they sink to the end.
    lo_name names the low word of a two-column int64 key (block.py KEY_LO):
    it joins the sort keys so runs are sorted by the full 64-bit key.
    Returns (cols, bucket), both permuted.

    impl='radix'/'radix4': the LSD radix form — key word passes plus ONE
    narrow pass for the bucket as the most significant word (8-bit
    buckets; n_shards tells the radix path the bucket range, and values
    past 254 keep lax.sort)."""
    capacity = bucket.shape[0]
    key = cols[key_name]
    if impl.startswith("radix") and n_shards is not None \
            and n_shards < 255 \
            and (lo_name is not None or _radix_supported(key)):
        # bucket values (incl. the ghost n_shards) fit the 8-bit word
        key_cols = ([cols[lo_name], key] if lo_name is not None
                    else [key])
        words = orderable_words(key_cols)
        word_bits = [32] * len(words)
        words.append(lax.bitcast_convert_type(bucket, jnp.uint32))
        word_bits.append(8)
        order = radix_sort_perm(words, count, bits=4 if impl == "radix4"
                                else 8, word_bits=word_bits)
        out = gather_rows(cols, order)
        return out, jnp.take(bucket, order)
    if impl == "packed" and (lo_name is not None or _radix_supported(key)):
        # LSD packed passes: key word(s) then the bucket as the most
        # significant word — one fast single-operand sort per word
        # instead of one slow multi-operand comparator sort.
        key_cols = ([cols[lo_name], key] if lo_name is not None
                    else [key])
        words = orderable_words(key_cols)
        words.append(_orderable_u32(bucket, False))
        order = packed_sort_perm(words, count)
        out = gather_rows(cols, order)
        return out, jnp.take(bucket, order)
    perm_src = lax.iota(jnp.int32, capacity)
    if lo_name is None:
        sorted_bucket, sorted_key, perm = lax.sort(
            (bucket, cols[key_name], perm_src), num_keys=2, is_stable=True
        )
        sorted_keys = {key_name: sorted_key}
    else:
        sorted_bucket, sk, sl, perm = lax.sort(
            (bucket, cols[key_name], cols[lo_name], perm_src),
            num_keys=3, is_stable=True,
        )
        sorted_keys = {key_name: sk, lo_name: sl}
    out = gather_rows(
        {n: c for n, c in cols.items() if n not in sorted_keys}, perm
    )
    out.update(sorted_keys)  # already produced by the sort; skip gathers
    return out, sorted_bucket


def _orderable_u32(word: jax.Array, is_float: bool) -> jax.Array:
    """Map a 32-bit word to uint32 whose UNSIGNED order equals the source
    order: ints flip the sign bit; floats use the sign-magnitude flip
    (negative floats reverse). Radix digit source."""
    u = lax.bitcast_convert_type(word, jnp.uint32)
    if is_float:
        mask = jnp.where((u >> jnp.uint32(31)) != jnp.uint32(0),
                         jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000))
        return u ^ mask
    return u ^ jnp.uint32(0x80000000)


def radix_sort_perm(words, count: jax.Array,
                    descending: bool = False, bits: int = 8,
                    word_bits=None) -> jax.Array:
    """Stable LSD radix sort permutation over orderable-uint32 words
    (LEAST significant word first); ghost rows (index >= count) sink to
    the end. Each pass streams the digits once through the Pallas
    histogram + rank kernels on TPU (XLA equivalents elsewhere via
    lax.platform_dependent) and scatters only the still-needed words +
    the permutation — payload columns move ONCE, via the returned perm:
    output row j should be source row perm[j] (gather_rows semantics,
    same contract as the argsort order in sort_by_column).

    word_bits optionally gives each word's significant width (default 32
    each): a bucket id carried as the MOST significant word costs one
    8-bit pass instead of four — the radix form of the fused
    (bucket, key) multi-key sort. Narrow words must be value-bounded by
    their width; descending requires full-width words (the flip is ~w)."""
    from vega_tpu.tpu import pallas_kernels as pk

    if word_bits is None:
        word_bits = [32] * len(words)
    assert not (descending and any(b != 32 for b in word_bits))
    cap = words[0].shape[0]
    mask = valid_mask(cap, count)
    active = []
    for w, wb in zip(words, word_bits):
        if descending:
            w = ~w
        # ghosts get the max significant value EVERY pass: they start
        # last and stay last under stability
        active.append(jnp.where(mask, w, jnp.uint32((1 << wb) - 1)))
    widths = list(word_bits)
    perm = lax.iota(jnp.int32, cap)
    n_bins = 1 << bits
    digit_mask = jnp.uint32(n_bins - 1)
    while active:
        word = active[0]
        for shift in range(0, widths[0], bits):
            d = ((word >> jnp.uint32(shift))
                 & digit_mask).astype(jnp.int32)
            hist = pk.radix_hist(d, n_bins)
            starts = (jnp.cumsum(hist) - hist).astype(jnp.int32)
            pos = pk.radix_pos(d, starts, n_bins)
            # pos is a full permutation (every digit in range): scatter
            # the still-needed words + perm
            active = [jnp.zeros_like(a).at[pos].set(a) for a in active]
            perm = jnp.zeros_like(perm).at[pos].set(perm)
            word = active[0]
        active = active[1:]  # this word's digits are consumed
        widths = widths[1:]
    return perm


def orderable_words(cols) -> list:
    """[_orderable_u32(c)] for a sequence of 32-bit columns — the shared
    radix word construction (sort_by_column, bucket_key_sort, and the
    take_ordered row sort all build word lists from columns; one site
    keeps the orderable encoding in lockstep)."""
    return [_orderable_u32(c, jnp.issubdtype(c.dtype, jnp.floating))
            for c in cols]


def _radix_supported(key: jax.Array) -> bool:
    return key.dtype in (jnp.dtype(jnp.int32), jnp.dtype(jnp.float32))


def resolve_backend_mode(name: str, value: str, allowed: tuple,
                         cpu_choice: str, other_choice: str) -> str:
    """Shared resolver for the per-backend 'auto' config knobs
    (dense_sort_impl, dense_rbk_plan, dense_table_plan): validate the
    string, then resolve 'auto' from the measured evidence — one choice
    on CPU, the conservative choice elsewhere until the queued on-chip
    A/Bs decide (env.py notes). Safe to ask the backend here: resolution
    happens at trace/materialize time, inside device work."""
    from vega_tpu.errors import VegaError

    if value not in allowed:
        raise VegaError(
            f"{name} must be one of {', '.join(repr(a) for a in allowed)};"
            f" got {value!r}")
    if value == "auto":
        return (cpu_choice if jax.default_backend() == "cpu"
                else other_choice)
    return value


def resolve_sort_impl() -> str:
    """Configuration.dense_sort_impl, validated and with 'auto' resolved
    per backend (packed on CPU — measured 3.8x on the dominant sort at
    bench shapes; xla on TPU until the queued on-chip A/B decides, see
    env.py). Read at trace time; callers put the resolved value in their
    program-cache keys. Lives here (not dense_rdd) so kernel-internal
    sort choices honor the same setting."""
    from vega_tpu.env import Env

    return resolve_backend_mode(
        "dense_sort_impl",
        getattr(Env.get().conf, "dense_sort_impl", "auto"),
        ("auto", "xla", "packed", "radix", "radix4"), "packed", "xla")


def packed_sort_perm(words, count: jax.Array,
                     descending: bool = False) -> jax.Array:
    """Stable sort permutation over orderable-uint32 words via
    SINGLE-OPERAND int64 sorts of (word << 31 | position).

    XLA:CPU's multi-operand comparator sort is 4-8x slower than its
    single-operand sort at bench shapes (5M rows: sort_key_val 2.01s,
    3-operand 2.69s, packed 0.53s — docs/BENCH_NOTES.md round 5), so
    packing the key and the permutation into one 63-bit word turns the
    sort+permutation problem into the fast single-column case. The
    position in the low 31 bits is also the stability tie-break. Words
    are LSD-first like radix_sort_perm (wide int64 keys: [lo, hi]);
    multi-word keys run one stable packed pass per word. Invalid rows
    (position >= count) sort last (their word is forced to the max;
    among max-ties the position tie-break keeps valid rows - which
    always occupy lower positions - in front). int64 exists only inside
    the scoped enable_x64 (the block dtype contract stays 32-bit).

    Requires capacity < 2^31 (position must fit 31 bits) — HBM bounds
    any real shard far below that."""
    capacity = words[0].shape[0]
    if capacity >= (1 << 31):
        raise ValueError("packed_sort_perm: capacity must fit 31 bits")
    mask = valid_mask(capacity, count)
    order = None
    from vega_tpu.tpu import compat

    with compat.enable_x64():
        idx0 = lax.iota(jnp.int64, capacity)
        for wi, w in enumerate(words):  # LSD -> MSD: one stable pass/word
            if descending:
                w = ~w
            w = jnp.where(mask, w, jnp.uint32(0xFFFFFFFF))

            def one_pass(w=w, order=order):
                wp = (w if order is None
                      else jnp.take(w, order, axis=0))
                # Dtype-explicit lax ops: scalar int64 literals (jnp.int64(31))
                # canonicalize to int32 tensors on jax < 0.5 even inside the
                # enable_x64 scope, which fails stablehlo verification for
                # shift_left — broadcast + convert is identical HLO on
                # current jax and correct on both.
                wp64 = lax.convert_element_type(wp, jnp.int64)
                shift = lax.convert_element_type(
                    jnp.full(wp.shape, 31, jnp.int32), jnp.int64)
                lowmask = lax.convert_element_type(
                    jnp.full(wp.shape, 0x7FFFFFFF, jnp.int32), jnp.int64)
                packed = lax.bitwise_or(lax.shift_left(wp64, shift), idx0)
                sw = lax.sort(packed)
                pos = lax.convert_element_type(
                    lax.bitwise_and(sw, lowmask), jnp.int32)
                return (pos if order is None
                        else jnp.take(order, pos, axis=0))

            if wi == 0:
                order = one_pass()
                continue
            # More-significant words are often CONSTANT across the valid
            # rows (wide int64 ids in a narrow band: the hi word of
            # BIG + small keys) — the pass would change nothing: valid
            # rows all tie (stable keeps the prior order) and ghosts,
            # already last with forced-max words, stay last. Skip it at
            # RUNTIME via cond, halving the sort cost for that shape.
            wmin = jnp.min(jnp.where(mask, w, jnp.uint32(0xFFFFFFFF)))
            wmax = jnp.max(jnp.where(mask, w, jnp.uint32(0)))
            order = lax.cond(wmin >= wmax,  # empty shards skip too
                             lambda order=order: order,
                             one_pass)
    return order


def partition_by_bucket(cols: Cols, bucket: jax.Array, n_shards: int,
                        prefer_low_memory: bool = False,
                        sort_impl: str = None
                        ) -> Tuple[Cols, jax.Array]:
    """Stable counting partition: rows become contiguous per bucket (the
    ghost bucket n_shards sinks last), preserving in-bucket row order —
    the sort-free way to feed a pregrouped exchange when rows are already
    key-sorted. This is the 'sort_partition' reduce plan's grouping step:
    key-only lax.sort -> map-side combine -> THIS, versus the fused
    plan's multi-key (bucket, key) lax.sort over all pre-combine rows.

    The counting path's one-hot/cumsum intermediates are O(capacity *
    n_shards) — capacity is the STATIC pre-combine size, not the shrunk
    row count — so callers bound it with prefer_low_memory (the
    _group_by_bucket escape hatch: a single-key stable argsort by bucket
    instead). Returns (grouped cols, grouped bucket)."""
    grouped, _cto, _starts = _group_by_bucket(
        dict(cols, __bucket=bucket), bucket, n_shards,
        prefer_low_memory=prefer_low_memory, sort_impl=sort_impl)
    b = grouped.pop("__bucket")
    return grouped, b


def range_bucket(bounds: jax.Array, keys: jax.Array,
                 ascending: bool, bounds_lo: jax.Array = None,
                 keys_lo: jax.Array = None) -> jax.Array:
    """Range-partition bucket ids from sorted split bounds (sort_by_key's
    partitioner). Shared by the exchange program and its sizing histogram —
    exact capacity sizing depends on the two staying bit-identical.
    (bounds_lo, keys_lo) carry the low word of two-column int64 keys."""
    if bounds_lo is None:
        if ascending:
            return jnp.searchsorted(bounds, keys).astype(jnp.int32)
        if jnp.issubdtype(keys.dtype, jnp.floating):
            return jnp.searchsorted(-bounds, -keys).astype(jnp.int32)
        # bitwise-not, not negation: -INT32_MIN wraps onto itself and
        # lands the most negative key in the first (largest) bucket
        return jnp.searchsorted(~bounds, ~keys).astype(jnp.int32)
    if not ascending:
        # bitwise-not is order-reversing for int32 with no INT_MIN
        # negation overflow; applied to both words it reverses the
        # lexicographic order.
        bounds, bounds_lo = ~bounds, ~bounds_lo
        keys, keys_lo = ~keys, ~keys_lo
    return searchsorted2(bounds, bounds_lo, keys, keys_lo).astype(jnp.int32)


def pregrouped_group(bucket: jax.Array, n_shards: int):
    """(counts_to, starts) for rows already contiguous per bucket — the
    histogram shortcut both exchanges use instead of _group_by_bucket."""
    from vega_tpu.tpu import pallas_kernels as _pk

    counts_all = _pk.bucket_hist(bucket, n_shards + 1)
    counts_to = counts_all[:n_shards]
    starts = (jnp.cumsum(counts_all) - counts_all)[:n_shards]
    return counts_to, starts


def bucket_exchange(
    cols: Cols,
    count: jax.Array,  # int32[] per-shard valid count
    bucket: jax.Array,  # int32[capacity] target shard per row
    n_shards: int,
    slot_capacity: int,  # C: max rows this shard sends to any one target
    out_capacity: int,  # per-shard capacity of the received block
    pregrouped: bool = False,  # rows already bucket-grouped (bucket_key_sort)
    sort_impl: str = None,  # caller's resolved dense_sort_impl (cache-keyed)
) -> Tuple[Cols, jax.Array, jax.Array]:
    """All-to-all by bucket id. Returns (cols, new_count, overflow_flag).

    Map side: stable-sort rows by target bucket, slice into n_shards slots of
    slot_capacity rows each. Wire: one lax.all_to_all per column over ICI.
    Reduce side: mask + compact received rows. This is the entire reference
    shuffle data plane (SURVEY.md §2.5) as one fused XLA program.

    With pregrouped=True the caller guarantees valid rows are already
    contiguous per target bucket (e.g. via bucket_key_sort) and the grouping
    pass collapses to a bincount."""
    capacity = bucket.shape[0]
    if n_shards == 1:
        return passthrough_exchange(cols, count, capacity, out_capacity)
    mask = valid_mask(capacity, count)
    bucket = jnp.where(mask, bucket, n_shards)  # invalid rows -> ghost bucket

    if pregrouped:
        counts_to, starts = pregrouped_group(bucket, n_shards)
        sorted_cols = cols
    else:
        sorted_cols, counts_to, starts = _group_by_bucket(
            cols, bucket, n_shards, sort_impl=sort_impl)
    overflow_send = jnp.any(counts_to > slot_capacity)

    # Build [n_shards, slot_capacity] send buffers per column.
    slot_rows = starts[:, None] + jnp.arange(slot_capacity)[None, :]
    slot_valid = jnp.arange(slot_capacity)[None, :] < counts_to[:, None]
    slot_rows = jnp.clip(slot_rows, 0, capacity - 1)

    send_counts = jnp.minimum(counts_to, slot_capacity).astype(jnp.int32)
    recv_counts = lax.all_to_all(
        send_counts, SHARD_AXIS, split_axis=0, concat_axis=0
    )

    received: Cols = {}
    for name, col in sorted_cols.items():
        buf = jnp.take(col, slot_rows, axis=0)  # [n_shards, C, ...]
        zero = jnp.zeros((), dtype=col.dtype)
        expand = slot_valid.reshape(slot_valid.shape + (1,) * (buf.ndim - 2))
        buf = jnp.where(expand, buf, zero)
        got = lax.all_to_all(buf, SHARD_AXIS, split_axis=0, concat_axis=0)
        received[name] = got.reshape((n_shards * slot_capacity,) + got.shape[2:])

    recv_valid = (
        jnp.arange(slot_capacity)[None, :] < recv_counts[:, None]
    ).reshape(-1)
    new_count = jnp.sum(recv_counts).astype(jnp.int32)
    overflow_recv = new_count > out_capacity
    out_cols, _ = compact(received, recv_valid, out_capacity)
    return out_cols, new_count, overflow_send | overflow_recv


# ---------------------------------------------------------------------------
# sorted-run segment operations (the reduce side)
# ---------------------------------------------------------------------------


def sort_by_column(cols: Cols, count: jax.Array, key_name: str,
                   descending: bool = False, lo_name: str = None,
                   impl: str = "xla") -> Cols:
    """Stable sort valid rows by one column (or a (key, lo) two-column
    int64 key when lo_name is given); invalid rows sink to the end.
    impl='radix' (Configuration.dense_sort_impl) uses the LSD radix path
    for int32/float32/wide keys — Pallas-streamed passes on TPU instead
    of lax.sort's comparator network; impl='packed' packs (key, perm)
    into one 63-bit word so the sort is XLA's fast single-operand case
    (packed_sort_perm). Unsupported dtypes keep lax.sort."""
    key = cols[key_name]
    if impl in ("radix", "radix4", "packed") and (
            lo_name is not None or _radix_supported(key)):
        if lo_name is not None:
            # wide int64: stored lo's signed order == true-lo unsigned
            # order, so the plain int transform applies to both words
            words = orderable_words([cols[lo_name], key])
        else:
            words = orderable_words([key])
        if impl == "packed":
            order = packed_sort_perm(words, count, descending)
        else:
            order = radix_sort_perm(words, count, descending,
                                    bits=4 if impl == "radix4" else 8)
        return gather_rows(cols, order)
    capacity = key.shape[0]
    mask = valid_mask(capacity, count)
    if lo_name is not None:
        hi_k, lo_k = key, cols[lo_name]
        if descending:
            hi_k, lo_k = ~hi_k, ~lo_k  # order-reversing, overflow-free
        hi_k = jnp.where(mask, hi_k, _orderable_max(hi_k))
        lo_k = jnp.where(mask, lo_k, _orderable_max(lo_k))
        perm_src = lax.iota(jnp.int32, capacity)
        _, _, order = lax.sort((hi_k, lo_k, perm_src), num_keys=2,
                               is_stable=True)
        return gather_rows(cols, order)
    if descending:
        k = _orderable(key)
        # bitwise-not is the overflow-free order flip for ints (negation
        # wraps INT32_MIN onto itself and mis-sorts it first); floats
        # negate exactly
        flipped = -k if jnp.issubdtype(k.dtype, jnp.floating) else ~k
        order = jnp.argsort(
            jnp.where(mask, flipped, _orderable_max(key)), stable=True
        )
    else:
        order = jnp.argsort(
            jnp.where(mask, _orderable(key), _orderable_max(key)), stable=True
        )
    return gather_rows(cols, order)


_WIDE_BIAS = 0x80000000  # sign-flip bias on stored low words (block._LO_BIAS)


def _wide_unbias(lo: jax.Array) -> jax.Array:
    """Stored (biased int32) low word -> true unsigned low word."""
    return lax.bitcast_convert_type(lo, jnp.uint32) ^ jnp.uint32(_WIDE_BIAS)


def _wide_rebias(lo_u: jax.Array) -> jax.Array:
    return lax.bitcast_convert_type(lo_u ^ jnp.uint32(_WIDE_BIAS), jnp.int32)


def wide_add(a_hi, a_lo, b_hi, b_lo):
    """int64 addition over the wide (hi int32, biased-lo int32) encoding:
    unsigned low-word add with carry into the high word. Wraps mod 2^64
    like numpy int64 (the host tier's python ints are exact bignums —
    the documented device dtype contract)."""
    au, bu = _wide_unbias(a_lo), _wide_unbias(b_lo)
    s = au + bu  # uint32 wrap
    carry = (s < au).astype(jnp.int32)
    return a_hi + b_hi + carry, _wide_rebias(s)


def wide_add_checked(a_hi, a_lo, b_hi, b_lo):
    """wide_add plus a signed-overflow predicate: operands of equal sign
    whose sum's sign differs wrapped past the int64 range. The final
    mod-2^64 value is still exact whenever the TRUE total fits int64, so a
    sticky OR of these per-pair flags through a reduction is a conservative
    "total may be out of range" detector (false positives possible under
    reassociation; never false negatives)."""
    au, bu = _wide_unbias(a_lo), _wide_unbias(b_lo)
    s = au + bu  # uint32 wrap
    carry = (s < au).astype(jnp.int32)
    r_hi = a_hi + b_hi + carry
    same_sign = (a_hi < 0) == (b_hi < 0)
    ovf = same_sign & ((r_hi < 0) != (a_hi < 0))
    return r_hi, _wide_rebias(s), ovf


def wide_select(a_hi, a_lo, b_hi, b_lo, take_min: bool):
    """Lexicographic (hi, biased-lo) min/max — signed compares equal
    int64 order by construction of the encoding."""
    a_less = (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo < b_lo))
    pick_a = a_less if take_min else ~a_less
    return (jnp.where(pick_a, a_hi, b_hi), jnp.where(pick_a, a_lo, b_lo))


def _orderable(key: jax.Array) -> jax.Array:
    """Map a column to an order-preserving integer/float domain."""
    return key


def _orderable_max(key: jax.Array):
    if jnp.issubdtype(key.dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype=key.dtype)
    return jnp.array(jnp.iinfo(key.dtype).max, dtype=key.dtype)


def segment_reduce_sorted(
    cols: Cols,
    count: jax.Array,
    key_name: str,
    combine: Callable,  # (value_cols_a, value_cols_b) -> value_cols
    presorted: bool = False,
    lo_name: str = None,
    sort_impl: str = "xla",
) -> Tuple[Cols, jax.Array]:
    """Generic reduce_by_key over a shard: sort by key, then a segmented
    associative scan with an arbitrary traceable combiner; the last row of
    each segment carries the reduction. Returns compacted (cols, count).
    lo_name names the low word of a two-column int64 key: it sorts and
    segments with the key and rides to the output untouched.

    This is reference hot loop 2 (shuffled_rdd.rs:154-164 merge_combiners
    into a HashMap) recast as sort + scan so it vectorizes on the VPU instead
    of chasing hash buckets."""
    capacity = cols[key_name].shape[0]
    if not presorted:
        cols = sort_by_column(cols, count, key_name, lo_name=lo_name,
                              impl=sort_impl)
    mask = valid_mask(capacity, count)
    keys = cols[key_name]
    first = jnp.concatenate([
        jnp.ones((1,), jnp.bool_),
        keys[1:] != keys[:-1],
    ])
    if lo_name is not None:
        lo_col = cols[lo_name]
        first = first | jnp.concatenate([
            jnp.ones((1,), jnp.bool_), lo_col[1:] != lo_col[:-1],
        ])
    key_set = {key_name} if lo_name is None else {key_name, lo_name}
    value_cols = {n: c for n, c in cols.items() if n not in key_set}

    def seg_combine(a, b):
        va, fa = a
        vb, fb = b
        merged = combine(va, vb)
        out = jax.tree.map(
            lambda m, y: jnp.where(
                fb.reshape(fb.shape + (1,) * (m.ndim - 1)), y, m
            ),
            merged, vb,
        )
        return out, fa | fb

    scanned, _ = lax.associative_scan(seg_combine, (value_cols, first))
    # Segment end = next row starts a new segment, or this is the last valid row.
    idx = lax.iota(jnp.int32, capacity)
    next_first = jnp.concatenate([first[1:], jnp.ones((1,), jnp.bool_)])
    is_end = mask & (next_first | (idx == count - 1))
    out = dict(scanned)
    out[key_name] = keys
    if lo_name is not None:
        out[lo_name] = cols[lo_name]
    return compact(out, is_end, capacity)


_FAST_SEGMENT_OPS = {
    "add": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
    "prod": jax.ops.segment_prod,
}


def segment_reduce_named(
    cols: Cols, count: jax.Array, key_name: str, op: str,
    presorted: bool = False, lo_name: str = None, sort_impl: str = "xla",
) -> Tuple[Cols, jax.Array]:
    """Fast path for the common monoids via XLA segment ops. lo_name names
    the low word of a two-column int64 key (sorts/segments with the key)."""
    seg_op = _FAST_SEGMENT_OPS[op]
    capacity = cols[key_name].shape[0]
    if not presorted:
        cols = sort_by_column(cols, count, key_name, lo_name=lo_name,
                              impl=sort_impl)
    mask = valid_mask(capacity, count)
    keys = cols[key_name]
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), keys[1:] != keys[:-1]]
    )
    if lo_name is not None:
        lo_col = cols[lo_name]
        first = first | jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), lo_col[1:] != lo_col[:-1]]
        )
    first = first & mask
    seg_ids = jnp.cumsum(first.astype(jnp.int32)) - 1
    seg_ids = jnp.where(mask, seg_ids, capacity - 1)
    n_segments = jnp.sum(first).astype(jnp.int32)
    key_set = {key_name} if lo_name is None else {key_name, lo_name}
    out: Cols = {}
    for name, col in cols.items():
        if name in key_set:
            continue
        if op == "add" or op == "prod":
            neutral = jnp.zeros((), col.dtype) if op == "add" else jnp.ones((), col.dtype)
            masked = jnp.where(
                mask.reshape(mask.shape + (1,) * (col.ndim - 1)), col, neutral
            )
        else:
            masked = col
        out[name] = seg_op(masked, seg_ids, num_segments=capacity)
    # Key of segment i = key at the i-th segment start.
    start_rows = jnp.nonzero(first, size=capacity, fill_value=capacity - 1)[0]
    out[key_name] = jnp.take(keys, start_rows)
    if lo_name is not None:
        out[lo_name] = jnp.take(cols[lo_name], start_rows)
    seg_valid = lax.iota(jnp.int32, capacity) < n_segments
    comp, _ = compact(out, seg_valid, capacity)
    return comp, n_segments


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------


def ragged_expand(counts_per_row: jax.Array, out_capacity: int):
    """Slot ownership for ragged expansion: row i emits counts_per_row[i]
    contiguous output slots. Returns (owner, offset, total) where output
    slot j belongs to row owner[j] at position offset[j] within that row's
    run, and total is the exact output size (saturated to INT32_MAX if the
    int32 prefix sums wrapped — the caller must fail loudly, not truncate).
    Rows with count 0 never own a slot: the next row shares their start
    and wins the 'right'-side binary search. Shared by merge_join_expand
    and the device flat_map."""
    n_rows = counts_per_row.shape[0]
    m = counts_per_row
    starts = jnp.cumsum(m) - m
    total = jnp.sum(m).astype(jnp.int32)
    wrapped = (total < 0) | jnp.any(starts < 0)
    total = jnp.where(wrapped, jnp.int32(2**31 - 1), total)
    j = lax.iota(jnp.int32, out_capacity)
    owner = jnp.clip(jnp.searchsorted(starts, j, side="right") - 1,
                     0, n_rows - 1)
    offset = j - jnp.take(starts, owner)
    return owner, offset, total


def merge_join_expand(
    left: Cols, left_count: jax.Array,
    right: Cols, right_count: jax.Array,
    key_name: str,
    out_capacity: int,
    outer: bool = False,
    fill_value: float = 0,
    left_sorted: bool = False,   # caller guarantees valid-prefix + sorted
    right_sorted: bool = False,
    lo_name: str = None,         # low word of a two-column int64 key
    sort_impl: str = "xla",
) -> Tuple[Cols, jax.Array, jax.Array]:
    """General sort-merge join with duplicate keys on BOTH sides.

    Reference semantics (pair_rdd.rs:104-121 via cogroup): inner join emits
    the full dup x dup product per key; left outer emits every valid left
    row, with fill_value in right columns when unmatched. Static shapes:
    output rows are assigned by ragged expansion — per-left-row match
    counts -> exclusive prefix sums -> each output slot finds its owning
    left row by binary search — so the product materializes into a fixed
    out_capacity with an overflow flag (the exchange capacity-factor
    pattern; driver retries with a larger capacity). Output rows are
    key-sorted (left sort order), deterministic across capacities.

    Returns (cols, count, total) where count = min(total, out_capacity) and
    total is the exact full product size — the driver uses it to size the
    ONE retry exactly instead of growing geometrically (a dup x dup product
    can exceed any constant growth factor). Right columns appear as
    "r_<name>".
    """
    lcap = left[key_name].shape[0]
    rcap = right[key_name].shape[0]
    if not left_sorted:
        left = sort_by_column(left, left_count, key_name, lo_name=lo_name,
                              impl=sort_impl)
    if not right_sorted:
        right = sort_by_column(right, right_count, key_name,
                               lo_name=lo_name, impl=sort_impl)
    lkeys = left[key_name]
    rkeys = right[key_name]
    rmask = valid_mask(rcap, right_count)
    rkeys = jnp.where(rmask, rkeys, _orderable_max(rkeys))
    lmask = valid_mask(lcap, left_count)

    # Per-left-row match range in the sorted right block. The min() guards
    # clip sentinel-padded rows out when a valid key equals the sentinel.
    if lo_name is None:
        lo = jnp.minimum(jnp.searchsorted(rkeys, lkeys, side="left"),
                         right_count)
        hi = jnp.minimum(jnp.searchsorted(rkeys, lkeys, side="right"),
                         right_count)
    else:
        lkeys_lo = left[lo_name]
        rkeys_lo = jnp.where(rmask, right[lo_name],
                             _orderable_max(right[lo_name]))
        lo = jnp.minimum(
            searchsorted2(rkeys, rkeys_lo, lkeys, lkeys_lo, "left"),
            right_count,
        )
        hi = jnp.minimum(
            searchsorted2(rkeys, rkeys_lo, lkeys, lkeys_lo, "right"),
            right_count,
        )
    n_match = hi - lo
    if outer:
        m = jnp.where(lmask, jnp.maximum(n_match, 1), 0)
    else:
        m = jnp.where(lmask, n_match, 0)
    # Slot ownership via ragged_expand; total saturates to INT32_MAX when
    # a dup x dup product over 2^31 rows/shard would wrap (cannot
    # materialize anyway — 25+ GB of rows — but must fail loudly in the
    # driver, not return a truncated block).
    li, off, total = ragged_expand(m, out_capacity)
    ri = jnp.clip(jnp.take(lo, li) + off, 0, rcap - 1)
    row_matched = jnp.take(n_match > 0, li)

    key_set = {key_name} if lo_name is None else {key_name, lo_name}
    out: Cols = {key_name: jnp.take(lkeys, li)}
    if lo_name is not None:
        out[lo_name] = jnp.take(left[lo_name], li)
    for name, col in left.items():
        if name not in key_set:
            out[name] = jnp.take(col, li, axis=0)
    for name, col in right.items():
        if name in key_set:
            continue
        taken = jnp.take(col, ri, axis=0)
        if outer:
            fill = jnp.asarray(fill_value, dtype=col.dtype)
            mm = row_matched.reshape(row_matched.shape
                                     + (1,) * (taken.ndim - 1))
            taken = jnp.where(mm, taken, fill)
        out[f"r_{name}"] = taken
    # Valid output slots are the prefix [0, total) — already compact.
    count = jnp.minimum(total, out_capacity)
    return out, count, total


# ---------------------------------------------------------------------------
# misc per-shard reductions
# ---------------------------------------------------------------------------


def masked_reduce(col: jax.Array, count: jax.Array, op: str) -> jax.Array:
    mask = valid_mask(col.shape[0], count)
    m = mask.reshape(mask.shape + (1,) * (col.ndim - 1))
    if op == "add":
        return jnp.sum(jnp.where(m, col, 0), axis=0)
    if op == "min":
        return jnp.min(jnp.where(m, col, _orderable_max(col)), axis=0)
    if op == "max":
        if jnp.issubdtype(col.dtype, jnp.floating):
            lo = jnp.array(-jnp.inf, col.dtype)
        else:
            lo = jnp.array(jnp.iinfo(col.dtype).min, col.dtype)
        return jnp.max(jnp.where(m, col, lo), axis=0)
    raise ValueError(f"unknown reduction {op}")


# ---------------------------------------------------------------------------
# GF(256) decode kernel (coded shuffle, shuffle/coding.py)
# ---------------------------------------------------------------------------


def gf256_accumulate(blocks, coeffs) -> jax.Array:
    """XOR-accumulate GF(256)-scaled byte rows: out = XOR_i c_i * B_i.

    The vectorized decode step of the coded shuffle (shuffle/coding.py):
    `blocks` is uint8[n, L] length-framed byte columns (survivor buckets
    zero-padded to the frame width), `coeffs` is uint8[n] GF(256)
    coefficients — all ones for the XOR scheme, Cauchy-matrix entries
    for rs(k, m). Multiplication is two log-table gathers plus an exp
    gather with the zero operands masked (log(0) is undefined; a zero
    factor makes the product zero), so the whole decode is gather/where/
    xor work the VPU streams. Must stay bit-identical to the numpy twin
    coding._accumulate_np — test_dense.py asserts host-vs-device parity.
    """
    from vega_tpu.shuffle.coding import GF_EXP, GF_LOG

    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    coeffs = jnp.asarray(coeffs, dtype=jnp.uint8)
    exp_t = jnp.asarray(GF_EXP, dtype=jnp.uint8)
    log_t = jnp.asarray(GF_LOG, dtype=jnp.int32)
    logs = (jnp.take(log_t, blocks.astype(jnp.int32))
            + jnp.take(log_t, coeffs.astype(jnp.int32))[:, None])
    prod = jnp.take(exp_t, logs)
    prod = jnp.where((blocks == 0) | (coeffs == 0)[:, None],
                     jnp.uint8(0), prod)
    out = jnp.zeros(blocks.shape[1], dtype=jnp.uint8)
    # Group sizes are small (k ≤ 128, typically 4): a static unrolled
    # XOR chain beats a lax.reduce round trip on every jax version.
    for i in range(blocks.shape[0]):
        out = lax.bitwise_xor(out, prod[i])
    return out
