"""Ring exchange: peak-memory-bounded alternative to the all_to_all shuffle.

bucket_exchange (kernels.py) materializes an [n_shards, slot_capacity] send
buffer per column — peak memory grows linearly with mesh size, which is the
HBM hazard for large blocks on big meshes. The ring exchange instead
processes ONE peer per step: select the rows destined for peer (i+s) mod n,
ppermute them s hops around the ring, and append what arrives — peak extra
memory is a single [slot_capacity] buffer per column regardless of mesh
size, at the cost of n-1 sequential collective steps.

This is the same ring-pipelining pattern ring attention uses for long
sequences (block exchange over ppermute instead of one big collective),
applied to keyed-data shuffles; lane-adjacent shifts ride neighbor ICI
links on a physical ring/torus. Cf. "Memory-efficient array redistribution
through portable collective communication" (arXiv:2112.01075), which builds
redistributions from the same bounded-footprint collective steps.

Select per shuffle with the exchange="ring" keyword
(DenseRDD.reduce_by_key/group_by_key/join/sort_by_key) or globally via
Configuration.dense_exchange / VEGA_TPU_DENSE_EXCHANGE=ring.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from vega_tpu.tpu import kernels
from vega_tpu.tpu.mesh import SHARD_AXIS

Cols = Dict[str, jax.Array]


def ring_exchange(
    cols: Cols,
    count: jax.Array,
    bucket: jax.Array,
    n_shards: int,
    slot_capacity: int,
    out_capacity: int,
    pregrouped: bool = False,
    sort_impl: str = None,
) -> Tuple[Cols, jax.Array, jax.Array]:
    """Drop-in replacement for kernels.bucket_exchange (same contract:
    returns (cols, new_count, overflow_flag); pregrouped means rows are
    already contiguous per bucket, so grouping collapses to a bincount;
    sort_impl is the caller's resolved dense_sort_impl, threaded so the
    grouping escape hatch matches the caller's program-cache key)."""
    capacity = bucket.shape[0]
    if n_shards == 1:
        return kernels.passthrough_exchange(cols, count, capacity,
                                            out_capacity)
    mask = kernels.valid_mask(capacity, count)
    bucket = jnp.where(mask, bucket, n_shards)

    if pregrouped:
        counts_to, starts = kernels.pregrouped_group(bucket, n_shards)
        sorted_cols = cols
    else:
        # prefer_low_memory: the counting sort's O(capacity * n_shards)
        # intermediates would defeat exactly the peak-memory bound this
        # exchange exists to provide.
        sorted_cols, counts_to, starts = kernels._group_by_bucket(
            cols, bucket, n_shards, prefer_low_memory=True,
            sort_impl=sort_impl,
        )
    overflow = jnp.any(counts_to > slot_capacity)

    my_id = lax.axis_index(SHARD_AXIS)

    out_cols: Cols = {
        name: jnp.zeros((out_capacity,) + col.shape[1:], col.dtype)
        for name, col in cols.items()
    }
    write_pos = jnp.zeros((), jnp.int32)

    def take_slot(target):
        """[slot_capacity] rows destined for `target` + their count."""
        start = jnp.take(starts, target)
        n_rows = jnp.minimum(jnp.take(counts_to, target),
                             slot_capacity).astype(jnp.int32)
        rows = start + jnp.arange(slot_capacity)
        rows = jnp.clip(rows, 0, capacity - 1)
        slot = {name: jnp.take(col, rows, axis=0)
                for name, col in sorted_cols.items()}
        valid = jnp.arange(slot_capacity) < n_rows
        slot = {
            name: jnp.where(
                valid.reshape(valid.shape + (1,) * (c.ndim - 1)), c,
                jnp.zeros((), c.dtype),
            )
            for name, c in slot.items()
        }
        return slot, n_rows

    def append(out_cols, write_pos, slot, n_rows):
        idx = write_pos + jnp.arange(slot_capacity)
        in_range = jnp.arange(slot_capacity) < n_rows
        idx = jnp.where(in_range, idx, out_capacity)  # OOB rows dropped
        new = {
            name: out.at[idx].set(slot[name], mode="drop")
            for name, out in out_cols.items()
        }
        return new, write_pos + n_rows

    # Step 0: my own bucket stays local.
    slot, n_rows = take_slot(my_id)
    out_cols, write_pos = append(out_cols, write_pos, slot, n_rows)

    # Steps 1..n-1: send to peer (i+s) mod n via an s-hop shifted ppermute.
    # The loop is unrolled (perm must be static); each step's live buffer is
    # one [slot_capacity] slot per column.
    for s in range(1, n_shards):
        perm = [(i, (i + s) % n_shards) for i in range(n_shards)]
        target = (my_id + s) % n_shards
        slot, n_rows = take_slot(target)
        recv = {
            name: lax.ppermute(c, SHARD_AXIS, perm)
            for name, c in slot.items()
        }
        recv_rows = lax.ppermute(n_rows, SHARD_AXIS, perm)
        out_cols, write_pos = append(out_cols, write_pos, recv, recv_rows)

    total_in = write_pos
    # Rows destined for me but truncated by slot_capacity at any sender are
    # invisible here; senders flag that via `overflow` (any counts_to > slot).
    overflow = overflow | (total_in > out_capacity)
    return out_cols, total_in.astype(jnp.int32), overflow
