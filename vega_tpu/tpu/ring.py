"""Ring + staged exchanges: peak-memory-bounded alternatives to all_to_all.

bucket_exchange (kernels.py) materializes an [n_shards, slot_capacity] send
buffer per column — peak memory grows linearly with mesh size, which is the
HBM hazard for large blocks on big meshes. The bounded exchanges here
instead move rows in ROUNDS of `group` peers each: per round, each shard
selects the rows destined for peers (i+s) mod n for the round's shifts s,
ppermutes them around the ring sharing one stacked [group, slot_capacity]
send/recv buffer per column, and bulk-appends what arrives in ONE scatter
— peak extra memory is 3*group slots per column regardless of mesh size
(send slots + received mirrors + the append's stacked contiguous copy —
the coefficient exchange_plan.transient_rows charges), at
ceil((n-1)/group) sequential rounds.

group interpolates the whole trade: group=1 is the classic ring (a single
bounded buffer, n-1 rounds — ring_exchange delegates here); group=n-1 is
one round whose buffers match the all_to_all footprint. The collective-
aware planner (tpu/exchange_plan.py) picks the group per launch so the
estimated peak fits Configuration.dense_hbm_budget — the decomposition of
"Memory-efficient array redistribution through portable collective
communication" (arXiv:2112.01075): arbitrary reshards as *sequences* of
bounded-footprint collective blocks. Lane-adjacent shifts ride neighbor
ICI links on a physical ring/torus (the ring-attention pipelining
pattern applied to keyed-data shuffles).

Select per shuffle with the exchange= keyword
(DenseRDD.reduce_by_key/group_by_key/join/sort_by_key) or globally via
Configuration.dense_exchange / VEGA_TPU_DENSE_EXCHANGE: "auto" (default)
routes through the planner, "ring"/"staged"/"all_to_all" force a program.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from vega_tpu.tpu import kernels
from vega_tpu.tpu.mesh import SHARD_AXIS

Cols = Dict[str, jax.Array]


def ring_exchange(
    cols: Cols,
    count: jax.Array,
    bucket: jax.Array,
    n_shards: int,
    slot_capacity: int,
    out_capacity: int,
    pregrouped: bool = False,
    sort_impl: str = None,
) -> Tuple[Cols, jax.Array, jax.Array]:
    """Drop-in replacement for kernels.bucket_exchange (same contract:
    returns (cols, new_count, overflow_flag)): the group=1 extreme of the
    staged exchange — one bounded [slot_capacity] buffer per column,
    n-1 sequential ppermute rounds."""
    if n_shards == 1:
        return kernels.passthrough_exchange(cols, count, bucket.shape[0],
                                            out_capacity)
    return staged_exchange(cols, count, bucket, n_shards, slot_capacity,
                           out_capacity, pregrouped=pregrouped,
                           sort_impl=sort_impl, group=1)


def staged_exchange(
    cols: Cols,
    count: jax.Array,
    bucket: jax.Array,
    n_shards: int,
    slot_capacity: int,
    out_capacity: int,
    pregrouped: bool = False,
    sort_impl: str = None,
    group: int = 1,
) -> Tuple[Cols, jax.Array, jax.Array]:
    """Blocked/staged exchange: rows move in ceil((n-1)/group) rounds of
    `group` shifted ppermutes each. Same contract as
    kernels.bucket_exchange — returns (cols, new_count, overflow_flag);
    pregrouped means rows are already contiguous per bucket, so grouping
    collapses to a bincount; sort_impl is the caller's resolved
    dense_sort_impl, threaded so the grouping escape hatch matches the
    caller's program-cache key.

    Per round the live transient per column is one stacked
    [group, slot_capacity] send buffer plus its received mirror, and the
    round's arrivals land in ONE bulk scatter into the output — fewer
    O(out_capacity) append passes than the classic ring (rounds, not
    n-1) while the peak stays bounded at 2*group slots. The planner
    (tpu/exchange_plan.py) chooses `group` so that bound fits the HBM
    budget."""
    capacity = bucket.shape[0]
    if n_shards == 1:
        return kernels.passthrough_exchange(cols, count, capacity,
                                            out_capacity)
    group = max(1, min(int(group), n_shards - 1))
    mask = kernels.valid_mask(capacity, count)
    bucket = jnp.where(mask, bucket, n_shards)

    if pregrouped:
        counts_to, starts = kernels.pregrouped_group(bucket, n_shards)
        sorted_cols = cols
    else:
        # prefer_low_memory: the counting sort's O(capacity * n_shards)
        # intermediates would defeat exactly the peak-memory bound this
        # exchange exists to provide.
        sorted_cols, counts_to, starts = kernels._group_by_bucket(
            cols, bucket, n_shards, prefer_low_memory=True,
            sort_impl=sort_impl,
        )
    overflow = jnp.any(counts_to > slot_capacity)

    my_id = lax.axis_index(SHARD_AXIS)

    out_cols: Cols = {
        name: jnp.zeros((out_capacity,) + col.shape[1:], col.dtype)
        for name, col in cols.items()
    }
    write_pos = jnp.zeros((), jnp.int32)

    def take_slot(target):
        """[slot_capacity] rows destined for `target` + their count."""
        start = jnp.take(starts, target)
        n_rows = jnp.minimum(jnp.take(counts_to, target),
                             slot_capacity).astype(jnp.int32)
        rows = start + jnp.arange(slot_capacity)
        rows = jnp.clip(rows, 0, capacity - 1)
        slot = {name: jnp.take(col, rows, axis=0)
                for name, col in sorted_cols.items()}
        valid = jnp.arange(slot_capacity) < n_rows
        slot = {
            name: jnp.where(
                valid.reshape(valid.shape + (1,) * (c.ndim - 1)), c,
                jnp.zeros((), c.dtype),
            )
            for name, c in slot.items()
        }
        return slot, n_rows

    def append_round(out_cols, write_pos, slots, rows_list):
        """Bulk-append one round's received slots: one scatter per column
        over the stacked [g, slot_capacity] buffer."""
        g = len(slots)
        rows_vec = jnp.stack(rows_list)                 # [g]
        offs = jnp.cumsum(rows_vec) - rows_vec          # exclusive prefix
        j = jnp.arange(slot_capacity)[None, :]
        idx = write_pos + offs[:, None] + j             # [g, slot]
        in_range = j < rows_vec[:, None]
        idx = jnp.where(in_range, idx, out_capacity)    # OOB rows dropped
        flat_idx = idx.reshape(-1)
        new = {}
        for name, out in out_cols.items():
            stacked = jnp.stack([s[name] for s in slots])  # [g, slot, ...]
            flat = stacked.reshape((g * slot_capacity,)
                                   + stacked.shape[2:])
            new[name] = out.at[flat_idx].set(flat, mode="drop")
        return new, write_pos + jnp.sum(rows_vec)

    # Round 0: my own bucket stays local.
    slot, n_rows = take_slot(my_id)
    out_cols, write_pos = append_round(out_cols, write_pos, [slot],
                                       [n_rows])

    # Rounds of `group` shifts: send to peer (i+s) mod n via an s-hop
    # shifted ppermute. The loop is unrolled (perms must be static); each
    # round's live buffers are the stacked [group, slot] send slots and
    # their received mirrors.
    for r0 in range(1, n_shards, group):
        recv_slots = []
        recv_rows = []
        for s in range(r0, min(r0 + group, n_shards)):
            perm = [(i, (i + s) % n_shards) for i in range(n_shards)]
            target = (my_id + s) % n_shards
            slot, n_rows = take_slot(target)
            recv_slots.append({
                name: lax.ppermute(c, SHARD_AXIS, perm)
                for name, c in slot.items()
            })
            recv_rows.append(lax.ppermute(n_rows, SHARD_AXIS, perm))
        out_cols, write_pos = append_round(out_cols, write_pos,
                                           recv_slots, recv_rows)

    total_in = write_pos
    # Rows destined for me but truncated by slot_capacity at any sender are
    # invisible here; senders flag that via `overflow` (any counts_to > slot).
    overflow = overflow | (total_in > out_capacity)
    return out_cols, total_in.astype(jnp.int32), overflow
