"""Columnar partition blocks: the device tier's unit of data.

A Block is the TPU-native replacement for the reference's per-partition item
iterators (rdd/rdd.rs:179-183): named columns stored as one global array each,
sharded row-wise over the mesh, plus a per-shard valid-row count. Static
per-shard capacity keeps every shape XLA-compilable; raggedness lives in
`counts`, never in shapes (SURVEY.md §7 hard part 1).

Layout: each column is [n_shards * capacity, ...] sharded on axis 0; rows
[s*capacity, s*capacity + counts[s]) are shard s's valid rows.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vega_tpu.lint.sync_witness import named_lock
from vega_tpu.tpu import mesh as mesh_lib

_host_cache_lock = named_lock("tpu.block._host_cache_lock")  # serializes Block.host_cols fills

KEY = "k"  # canonical key column
VALUE = "v"  # canonical value column
# Wide (two-column int64) encoding. TPUs have no native int64 and jax x64
# is off, so an int64 column beyond int32 range splits into
# <name> = high 32 bits (signed: preserves order) and <name>.lo = low 32
# bits stored sign-bit-flipped (signed compare of the stored word ==
# unsigned compare of the true low word), making lexicographic
# (<name>, <name>.lo) order equal int64 order. Host-facing reads
# reassemble the int64 transparently. Keys AND value columns use the same
# encoding; the ".lo" suffix is reserved in user column names.
LO_SUFFIX = ".lo"
KEY_LO = KEY + LO_SUFFIX
_LO_BIAS = np.uint32(0x80000000)


def lo_of(name: str) -> str:
    return name + LO_SUFFIX


def is_lo(name: str) -> bool:
    return name.endswith(LO_SUFFIX)


def wide_value_pairs(names) -> dict:
    """{base: base+'.lo'} for every NON-KEY wide column pair present."""
    s = set(names)
    return {nm: lo_of(nm) for nm in s
            if not is_lo(nm) and nm != KEY and lo_of(nm) in s}


def encode_i64(src: np.ndarray):
    """int64 column -> (hi int32, biased-lo int32), order-preserving."""
    a = src.astype(np.int64, copy=False)
    hi = (a >> 32).astype(np.int32)
    lo = ((a & np.int64(0xFFFFFFFF)).astype(np.uint32)
          ^ _LO_BIAS).view(np.int32)
    return hi, lo


def decode_i64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Inverse of encode_i64."""
    lo_u = (np.asarray(lo).view(np.uint32) ^ _LO_BIAS).astype(np.int64)
    return (np.asarray(hi).astype(np.int64) << 32) | lo_u


def _decode_key_cols(cols: dict) -> dict:
    """Reassemble every (name, name.lo) wide pair — key or value — into
    one int64 column for host-facing reads; other columns pass through
    (order preserved)."""
    if not any(is_lo(n) for n in cols):
        return cols
    out = {}
    for name, col in cols.items():
        if is_lo(name):
            continue
        lo = cols.get(lo_of(name))
        out[name] = col if lo is None else decode_i64(col, lo)
    return out


def _decode_dict_cols(cols: dict, dicts) -> dict:
    """Turn dictionary-encoded int32 code columns back into their string
    columns for host-facing reads (the collect-boundary decode of
    tpu/dict_encoding.py); non-dict columns pass through (order
    preserved). Runs AFTER _decode_key_cols — dict names never carry a
    '.lo' pair, so the two decodes touch disjoint columns."""
    if not dicts:
        return cols
    return {name: (dicts[name][np.asarray(col)] if name in dicts else col)
            for name, col in cols.items()}


@dataclasses.dataclass
class Block:
    cols: Dict[str, jax.Array]  # each [n_shards * capacity, ...]
    counts: jax.Array  # int32[n_shards], valid rows per shard
    capacity: int  # per-shard row capacity (static)
    mesh: object  # jax.sharding.Mesh
    # Host copy of counts, cached: every device_get is a driver<->device
    # round trip (through the axon tunnel: a full network RTT), and the
    # drivers of count()/exchanges/collect all need counts. Builders that
    # know the counts (from_numpy, block_range, exchange drivers that
    # already fetched them with the overflow flag) pass them in; otherwise
    # the first counts_np fetches once.
    counts_host: Optional[np.ndarray] = None
    # Speculative blocks (dense_rdd deferred-overflow exchanges) carry a
    # settle callable: it batches every pending overflow-flag fetch into
    # one transfer and, on a failed speculation, repairs this block IN
    # PLACE (same object identity) from a clean re-materialization. Any
    # host-facing read must settle first — reading counts or columns of
    # an unsettled speculative block could observe capacity-truncated
    # data.
    settle: Optional[object] = None
    # Dictionary sidecar for string columns (tpu/dict_encoding.py):
    # {column name -> sorted host numpy array of dictionary values}, where
    # the column holds int32 codes indexing it. Host metadata only — never
    # shipped to device. None when no column is dictionary-encoded.
    dicts: Optional[Dict[str, np.ndarray]] = None
    # Multi-process only: replicated host copy of all columns, filled by
    # the first shard_rows (each host read there costs a full-block
    # all-gather; per-split consumption reads every shard).
    _host_cols_cache: Optional[Dict[str, np.ndarray]] = None

    def host_cols(self) -> Dict[str, np.ndarray]:
        """Replicated host copy of all columns, gathered once.

        The fill is serialized (double-checked lock): two scheduler task
        threads must not both dispatch the replicate-gather collective —
        in a multi-process mesh every process has to dispatch the same
        collectives in the same order, and a duplicated gather on one
        process deadlocks the others. DenseRDD.splits() pre-fills this on
        the driver thread before task fan-out for the same reason."""
        if self._host_cols_cache is None:
            with _host_cache_lock:
                if self._host_cols_cache is None:
                    self._host_cols_cache = {
                        name: np.asarray(c) for name, c in
                        # vegalint: ignore[VG003] — serializing this gather IS the point: a duplicated replicate-gather collective deadlocks multi-process meshes (docstring above)
                        mesh_lib.host_get(dict(self.cols)).items()}
        return self._host_cols_cache

    @property
    def n_shards(self) -> int:
        return self.mesh.size

    @property
    def counts_np(self) -> np.ndarray:
        if self.settle is not None:
            self.settle()  # may replace cols/counts/capacity in place
        if self.counts_host is None:
            self.counts_host = np.asarray(mesh_lib.host_get(self.counts))
        return self.counts_host

    @property
    def num_rows(self) -> int:
        return int(np.sum(self.counts_np))

    @property
    def column_names(self) -> List[str]:
        return list(self.cols)

    @property
    def nbytes(self) -> int:
        """Device-resident bytes of this block (all columns, full static
        capacity — padding rows occupy HBM like any others). HBM
        accounting for materialized blocks; pre-materialization sizing
        (which only has row counts) lives in stream.planned_chunk_rows."""
        return sum(int(np.prod(c.shape)) * c.dtype.itemsize
                   for c in self.cols.values())

    def to_numpy(self) -> Dict[str, np.ndarray]:
        """Gather valid rows to host, shard order preserved. Two-column
        int64 keys (KEY_LO) come back as one int64 KEY column — host-facing
        consumers never see the encoding."""
        counts = self.counts_np
        # One transfer for every column (a separate device_get per column
        # is a round trip each through the axon tunnel). Multi-process:
        # share shard_rows' replicated cache — each miss is a full-block
        # all-gather.
        first = next(iter(self.cols.values()), None)
        if isinstance(first, jax.Array) and not first.is_fully_addressable:
            host_cols = self.host_cols()
        else:
            host_cols = {name: np.asarray(c) for name, c in
                         mesh_lib.host_get(dict(self.cols)).items()}
        out: Dict[str, List[np.ndarray]] = {n: [] for n in self.cols}
        for s in range(self.n_shards):
            lo = s * self.capacity
            c = int(counts[s])
            for name in self.cols:
                out[name].append(host_cols[name][lo:lo + c])
        gathered = {n: np.concatenate(parts) if parts else np.empty((0,))
                    for n, parts in out.items()}
        return _decode_dict_cols(_decode_key_cols(gathered), self.dicts)

    def shard_rows(self, shard: int) -> Dict[str, np.ndarray]:
        counts = self.counts_np
        lo = shard * self.capacity
        c = int(counts[shard])
        first = next(iter(self.cols.values()), None)
        if isinstance(first, jax.Array) and not first.is_fully_addressable:
            # Eager slicing of a non-fully-addressable column is not
            # defined; fetch whole columns once (replicated all-gather),
            # cache them on the block — per-split host consumption calls
            # shard_rows n_shards times — and slice on host. The numpy
            # (_HostMeshStub) and single-process cases below never touch
            # jax.process_count(): backend init can hang on a wedged
            # tunnel and host numpy must stay readable regardless.
            sliced = {name: np.asarray(col)[lo:lo + c]
                      for name, col in self.host_cols().items()}
        else:
            # Serialized: per-split host consumption runs on scheduler
            # task threads, and concurrent device slicing + device_get
            # from two threads deadlocks XLA:CPU's runtime on old jaxlibs
            # under --xla_force_host_platform_device_count on a 1-core
            # box (observed: one thread wedged dispatching the gather,
            # another inside device_get, 0% CPU). One lock here costs
            # nothing — the path is host-bound anyway — and removes the
            # interleaving entirely.
            with _host_cache_lock, mesh_lib.device_door():
                # vegalint: ignore[VG003] — serializing this device_get IS the fix: concurrent slice+device_get from two task threads deadlocks old XLA:CPU on 1 core (CLAUDE.md)
                sliced = jax.device_get(
                    {name: col[lo:lo + c] for name, col in self.cols.items()}
                )  # one transfer for all columns
        return _decode_dict_cols(
            _decode_key_cols(
                {name: np.asarray(col) for name, col in sliced.items()}
            ),
            self.dicts,
        )


def _round_capacity(c: int) -> int:
    """Round per-shard capacity to a shape-stable bucket.

    Below 1M rows: next power of two (>=128) — few distinct shapes, so the
    structural program cache (dense_rdd.py) and XLA's jit cache stay hot
    across small pipelines. Above 1M: next multiple of 1M — pow2 would
    waste up to ~2x memory and sort work exactly where blocks are large
    (big jobs have few distinct shapes anyway). Both are multiples of 128
    (TPU lane width)."""
    c = max(c, 128)
    if c <= (1 << 20):
        return 1 << (c - 1).bit_length()
    step = 1 << 20
    return -(-c // step) * step


def _check_dtype(name: str, src: np.ndarray) -> np.ndarray:
    """Without jax x64, 64-bit inputs silently narrow to 32-bit on
    device_put. Narrowing int keys/values beyond int32 range would silently
    corrupt (key collisions, wrong sums) — refuse loudly; floats narrow with
    precision loss, which is the documented dtype contract."""
    import jax as _jax

    if src.dtype.kind in "OUS":
        # Strings were already dictionary-encoded upstream (from_numpy
        # runs encode_string_columns first), so anything still here is a
        # mixed-object column or a string column with encoding disabled.
        # jax.device_put would throw a raw TypeError — raise the crisp
        # VegaError instead so callers (RDD.dense, the frame planner)
        # degrade to the host tier.
        from vega_tpu.errors import VegaError

        raise VegaError(
            f"column {name!r} has dtype {src.dtype} which has no device "
            "representation (mixed Python objects, or strings with "
            "dense_dict_enabled=false) — use the host tier for this data."
        )
    if _jax.config.read("jax_enable_x64"):
        return src
    if src.dtype in (np.int64, np.uint64):
        narrow = np.uint32 if src.dtype == np.uint64 else np.int32
        info = np.iinfo(narrow)
        if len(src) and (src.min() < info.min or src.max() > info.max):
            from vega_tpu.errors import VegaError

            raise VegaError(
                f"column {name!r} has {src.dtype} values outside "
                f"{np.dtype(narrow)} range and jax x64 is disabled — values "
                "would silently collide. Enable x64 "
                "(jax.config.update('jax_enable_x64', True)) or use the "
                "host tier for this data."
            )
        return src.astype(narrow)
    if src.dtype == np.float64:
        return src.astype(np.float32)
    return src


def encode_key_columns(columns: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Split an int64 KEY column that does not fit int32 into the
    (KEY, KEY_LO) two-column encoding; in-range integer keys keep the
    cheaper single-column narrow path (block._check_dtype). Idempotent —
    already-encoded columns pass through (the streamed source pre-encodes
    on the FULL column so every chunk gets the same schema regardless of
    its local key range)."""
    if KEY_LO in columns:
        if KEY not in columns or \
                np.asarray(columns[KEY_LO]).dtype != np.int32:
            from vega_tpu.errors import VegaError

            raise VegaError(
                f"column name {KEY_LO!r} is reserved for the low word of "
                "two-column int64 keys"
            )
        return columns
    src = columns.get(KEY)
    if src is None:
        return columns
    src = np.asarray(src)
    if src.dtype not in (np.int64, np.uint64):
        return columns
    if len(src) == 0:
        return columns
    if src.dtype == np.uint64 and src.max() > np.uint64(2**63 - 1):
        from vega_tpu.errors import VegaError

        raise VegaError(
            "uint64 keys beyond int64 range are not representable on "
            "device — use the host tier for this data"
        )
    info = np.iinfo(np.int32)
    if info.min <= src.min() and src.max() <= info.max:
        return columns  # fits int32; _check_dtype narrows it
    hi, lo = encode_i64(src)
    out: Dict[str, np.ndarray] = {}
    for name, col in columns.items():
        if name == KEY:
            out[KEY] = hi
            out[KEY_LO] = lo
        else:
            out[name] = col
    return out


def encode_value_columns(columns: Dict[str, np.ndarray]
                         ) -> Dict[str, np.ndarray]:
    """Split int64 NON-key columns beyond int32 range into the wide
    (name, name.lo) encoding; in-range integers keep the narrow path.
    Idempotent like encode_key_columns (pre-encoded ".lo" columns pass
    through — the streamed source encodes ONCE on the full column so
    every chunk gets the same schema, then slices)."""
    out: Dict[str, np.ndarray] = {}
    for name, col in columns.items():
        if is_lo(name):
            out[name] = col  # pre-encoded (streamed chunks)
            continue
        src = np.asarray(col)
        if name == KEY or src.dtype not in (np.int64, np.uint64):
            out[name] = col
            continue
        if src.dtype == np.uint64 and len(src) and \
                src.max() > np.uint64(2**63 - 1):
            from vega_tpu.errors import VegaError

            raise VegaError(
                f"uint64 column {name!r} beyond int64 range is not "
                "representable on device — use the host tier"
            )
        info = np.iinfo(np.int32)
        in_range = (len(src) == 0
                    or (info.min <= src.min() and src.max() <= info.max))
        if in_range:
            out[name] = col  # fits int32; _check_dtype narrows it
            continue
        hi, lo = encode_i64(src)
        out[name] = hi
        out[lo_of(name)] = lo
    return out


def from_numpy(columns: Dict[str, np.ndarray], mesh=None,
               capacity: Optional[int] = None,
               wide_values: bool = True,
               dicts: Optional[Dict[str, np.ndarray]] = None) -> Block:
    """Build a row-sharded Block from host columns (equal lengths). int64
    columns beyond int32 range are transparently stored as two-column
    (name, name.lo) encodings (see LO_SUFFIX above) — the KEY via
    encode_key_columns, value columns via encode_value_columns (unless
    wide_values=False, for layouts with no wide form: the caller then
    degrades to the host tier on the VegaError _check_dtype raises).
    String columns dictionary-encode into int32 codes plus a dicts
    sidecar (tpu/dict_encoding.py); pre-encoded callers (parquet
    dictionary pages, streamed chunks) pass the code columns plus their
    `dicts` directly. With dense_dict_enabled off, strings raise the same
    crisp VegaError — callers degrade to the host tier."""
    from vega_tpu.tpu import dict_encoding

    mesh = mesh or mesh_lib.default_mesh()
    n_shards = mesh.size
    # Strings first: their codes are plain int32 columns for the int64
    # wide encodes below (which pass them through untouched).
    columns, dicts = dict_encoding.encode_string_columns(
        dict(columns), dicts)
    columns = encode_key_columns(columns)
    if wide_values:
        columns = encode_value_columns(columns)
    names = list(columns)
    n = len(columns[names[0]]) if names else 0
    per = -(-n // n_shards) if n else 0
    cap = _round_capacity(capacity or max(per, 1))
    counts = np.zeros(n_shards, dtype=np.int32)
    cols = {}
    for name in names:
        src = _check_dtype(name, np.asarray(columns[name]))
        dst = np.zeros((n_shards * cap,) + src.shape[1:], dtype=src.dtype)
        for s in range(n_shards):
            lo, hi = s * per, min((s + 1) * per, n)
            c = max(0, hi - lo)
            counts[s] = c
            if c:
                dst[s * cap:s * cap + c] = src[lo:hi]
        cols[name] = mesh_lib.host_put(dst, mesh_lib.shard_spec(mesh))
    counts_arr = mesh_lib.host_put(counts, mesh_lib.shard_spec(mesh))
    return Block(cols=cols, counts=counts_arr, capacity=cap, mesh=mesh,
                 counts_host=counts, dicts=dicts)


def block_range(n: int, mesh=None, dtype=jnp.int32, start: int = 0) -> Block:
    """Lazy iota block: shard s holds [start+s*per, start+s*per+count_s) —
    the device analogue of ctx.range (reference: context.rs:422-442), built
    on device with no host materialization. `start` offsets the whole range
    (used by the chunked/streamed source)."""
    from jax.sharding import PartitionSpec as P

    mesh = mesh or mesh_lib.default_mesh()
    n_shards = mesh.size
    per = -(-n // n_shards)
    cap = _round_capacity(per)
    counts_host = np.array(
        [max(0, min(per, n - s * per)) for s in range(n_shards)],
        dtype=np.int32,
    )

    def build():
        # axis_index instead of a device_put'd shard-id input: keeps the
        # source fully device-built and multiprocess-safe (no host array
        # to place on non-addressable devices).
        base = start + jax.lax.axis_index(mesh_lib.SHARD_AXIS) * per
        return base + jax.lax.iota(dtype, cap)

    from vega_tpu.tpu import compat

    build_sharded = jax.jit(
        compat.shard_map(
            build, mesh=mesh, in_specs=(),
            out_specs=P(mesh_lib.SHARD_AXIS),
        )
    )
    vals = build_sharded()
    counts = mesh_lib.host_put(counts_host, mesh_lib.shard_spec(mesh))
    return Block(cols={VALUE: vals}, counts=counts, capacity=cap, mesh=mesh,
                 counts_host=counts_host)


def single_column(values, mesh=None) -> Block:
    # Keyless int64 columns beyond int32 range use the wide (VALUE,
    # VALUE.lo) encoding like every other column: named reductions fold
    # the pair on device (dense_rdd._named_reduce_wide) and row-wise
    # closures fall back to the host tier, which sees decoded int64s.
    return from_numpy({VALUE: np.asarray(values)}, mesh)


def pair_block(keys, values, mesh=None) -> Block:
    return from_numpy({KEY: np.asarray(keys), VALUE: np.asarray(values)}, mesh)
