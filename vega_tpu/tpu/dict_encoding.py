"""Dictionary encoding: string columns on the device tier.

TPUs have no string dtype, so a string column becomes an int32 CODE column
plus a host-side dictionary sidecar on the Block (block.Block.dicts):
``dicts[name][code] == original string``. The dictionary is built SORTED
(np.unique), so codes are RANK codes — comparing codes compares strings
(lexicographically), which means sort / take_ordered / min / max run on the
codes directly with no extra pass. Equality ops (group_by, join, distinct,
count_by_key) ride the existing exchange/segment-reduce kernels unchanged:
codes are just another int32 column.

Two blocks encoded independently carry DIFFERENT dictionaries, so their
codes are not comparable; dense_rdd._DictUnifyRDD remaps both sides onto
one merged dictionary (host-side merge here + one device remap program
there) lazily before any keyed binary op. Decode happens only at the
collect boundary (block._decode_dict_cols).

Hash contract note (CLAUDE.md): HOST placement keeps splitmix64 on the
string bytes; DEVICE bucketing hashes the codes. Placement may differ
between tiers — only results must match, and the parity tests assert they
do.

Everything here is '<U'/'S' numpy arrays and int32 codes — never
object-dtype arrays (vegalint VG020: an object array must not reach a
shard program or device kernel).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

CODE_DTYPE = np.int32


def dict_enabled() -> bool:
    """Configuration.dense_dict_enabled, read lazily (env import cycles;
    callers may run before a Context exists)."""
    from vega_tpu.env import Env

    return bool(getattr(Env.get().conf, "dense_dict_enabled", True))


def dict_capacity() -> int:
    """Starting capacity of the staged unification remap tables
    (Configuration.dense_dict_capacity) — a real capacity: overflow sets
    the device flag and the driver retries doubled."""
    from vega_tpu.env import Env

    return int(getattr(Env.get().conf, "dense_dict_capacity", 65536))


def is_string_array(src: np.ndarray) -> bool:
    """True for columns that need dictionary encoding: unicode/bytes
    arrays, or object arrays whose every element is a str (the pandas /
    pyarrow to_numpy pivot shape). The object scan is a full pass, but it
    is the SOUND gate — sniffing only the first element would silently
    stringify mixed object columns."""
    if src.dtype.kind in ("U", "S"):
        return True
    if src.dtype.kind == "O":
        return len(src) > 0 and all(isinstance(x, str) for x in src.flat)
    return False


def encode_array(src: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """One string column -> (int32 codes, sorted dictionary values).

    np.unique returns the SORTED uniques and per-row indices into them, so
    the codes are rank codes by construction. Object arrays (all-str) are
    normalized to a fixed-width '<U' dictionary — no object dtype survives
    past this point."""
    src = np.asarray(src)
    if src.dtype.kind == "O":
        src = src.astype(np.str_)
    if len(src) == 0:
        return (np.zeros(0, dtype=CODE_DTYPE),
                np.zeros(0, dtype=src.dtype if src.dtype.kind in ("U", "S")
                         else "<U1"))
    values, codes = np.unique(src, return_inverse=True)
    return codes.astype(CODE_DTYPE, copy=False).reshape(-1), values


def encode_string_columns(
    columns: Dict[str, np.ndarray],
    dicts: Optional[Dict[str, np.ndarray]] = None,
) -> Tuple[Dict[str, np.ndarray], Optional[Dict[str, np.ndarray]]]:
    """Replace every string column with its int32 code column; returns
    (columns, dicts) where dicts maps encoded names to their sorted
    dictionaries (merged over any caller-provided pre-encoded `dicts` —
    the parquet/stream paths encode upstream and pass codes through).
    Idempotent for already-encoded columns (int32 codes pass straight
    through). With dense_dict_enabled=False, a string column raises the
    crisp VegaError the callers' fallback contract expects (dense_from_*
    degrade to the host tier on it)."""
    out_dicts: Dict[str, np.ndarray] = dict(dicts or {})
    out: Dict[str, np.ndarray] = {}
    enabled: Optional[bool] = None  # read the knob once, only if needed
    for name, col in columns.items():
        src = np.asarray(col)
        if not is_string_array(src):
            out[name] = col
            continue
        if enabled is None:
            enabled = dict_enabled()
        if not enabled:
            from vega_tpu.errors import VegaError

            raise VegaError(
                f"column {name!r} holds strings and dense_dict_enabled is "
                "off — string columns have no device form without "
                "dictionary encoding; use the host tier for this data"
            )
        codes, values = encode_array(src)
        out[name] = codes
        out_dicts[name] = values
    return out, (out_dicts or None)


def merge_dicts(left: np.ndarray, right: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Merge two sorted dictionaries into one sorted dictionary plus the
    per-side remap tables: merged[left_map[c]] == left[c] (resp. right).
    The remap is monotonic — both inputs and the merge are sorted — so
    remapped codes keep rank order and a key-sorted block stays
    key-sorted through the remap."""
    merged = np.union1d(left, right)
    left_map = np.searchsorted(merged, left).astype(CODE_DTYPE)
    right_map = np.searchsorted(merged, right).astype(CODE_DTYPE)
    return merged, left_map, right_map


def decode_codes(codes: np.ndarray, values: np.ndarray) -> np.ndarray:
    """codes -> strings via the dictionary (the collect-boundary decode).
    Out-of-range codes are a programming error upstream — indexing raises
    rather than papering over them."""
    codes = np.asarray(codes)
    if len(values) == 0 and len(codes) == 0:
        return np.zeros(0, dtype=values.dtype)
    return values[codes]
