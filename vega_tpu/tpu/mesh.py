"""Device mesh management.

The reference's parallel substrate is an executor fleet reached over TCP
(SURVEY.md §2.5); vega_tpu's is a jax.sharding.Mesh. One axis, "shards",
spans every addressable device: dense-RDD partitions map 1:1 onto mesh
shards, shuffles ride all_to_all over ICI, and multi-host meshes come from
jax.distributed (the DCN analogue of the reference's multi-host deployment,
context.rs:209-303).
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from vega_tpu.lint.sync_witness import named_lock

SHARD_AXIS = "shards"

_lock = named_lock("tpu.mesh._lock")
_default_mesh: Optional[Mesh] = None

# Serializes device program dispatch against host transfers on XLA:CPU.
_device_door = named_lock("tpu.mesh._device_door")


def device_door():
    """Mutual exclusion between device program dispatch and blocking host
    transfers, ON THE CPU BACKEND ONLY.

    Old XLA:CPU under --xla_force_host_platform_device_count on a 1-core
    box deadlocks when one thread sits inside jax.device_get while another
    dispatches a program (runtime pool starvation: the transfer waits on a
    computation whose execution needs the thread the dispatcher holds).
    Block.shard_rows' serialized device_get covered the slice+get pair;
    the same wedge fires between an exchange launch and a concurrent get
    (two cogroup partitions materializing their grouped sides on separate
    task threads). Every launch/transfer that can run on a scheduler task
    thread takes this door: shard_rows' get, host_get, and
    _run_exchange's program launches. On real accelerators this is a
    no-op context — dispatch and transfers pipeline freely. Callers must
    already be past backend init (the door itself reads
    jax.default_backend(), which must never run on import paths)."""
    if jax.default_backend() == "cpu":
        return _device_door
    return contextlib.nullcontext()


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   heartbeat_timeout_s: Optional[int] = None) -> None:
    """Join a multi-host device mesh via jax.distributed.

    The DCN analogue of the reference's multi-host deployment
    (context.rs:209-303 ssh bootstrap): every host runs the same program,
    jax.distributed glues their local chips into one global device set, and
    default_mesh() then spans all of them — collectives ride ICI within a
    slice and DCN across slices, inserted by XLA from the same shard_map
    programs. No code changes anywhere else: exchanges are mesh-size
    agnostic.

    Args default from the standard env vars (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID) or the TPU metadata service.

    Failure semantics (peer loss): a process that dies mid-pipeline stops
    heartbeating; the jax.distributed coordination service detects this
    within heartbeat_timeout_s (jax default 100s) and TERMINATES every
    surviving process with a fatal "another task died" error — a crisp,
    bounded failure instead of survivors hanging forever inside a
    collective that can no longer complete (the SPMD analogue of the
    reference's executor-loss detection,
    distributed_scheduler.rs:434-445; tested in
    tests/test_multihost.py::test_multihost_dense_peer_loss_fails_crisply).
    Lower heartbeat_timeout_s to tighten the bound."""
    coordinator, num_processes, process_id = _normalize_multihost(
        coordinator, num_processes, process_id)
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if heartbeat_timeout_s is not None:
        kwargs["heartbeat_timeout_seconds"] = heartbeat_timeout_s
    jax.distributed.initialize(**kwargs)
    set_default_mesh(None)  # rebuild over the now-global device set
    # The eviction-policy memo (LRU/weakref vs multi-process FIFO) was
    # possibly resolved under the pre-distributed single-process device
    # set; it must re-resolve over the now-global one.
    from vega_tpu.tpu import dense_rdd

    dense_rdd._reset_lifetime_multiproc_memo()
    global _multihost_settings, _multihost_heartbeat_s
    _multihost_settings = (coordinator, num_processes, process_id)
    # Record the EFFECTIVE timeout (jax's own default when none was
    # passed) so a later Context explicitly requesting that same value
    # is recognized as compatible, not spuriously rejected.
    _multihost_heartbeat_s = (heartbeat_timeout_s
                              if heartbeat_timeout_s is not None
                              else _jax_default_heartbeat_s())


_multihost_settings: Optional[tuple] = None  # set once per process
_multihost_heartbeat_s: Optional[int] = None  # the timeout actually applied


def _jax_default_heartbeat_s() -> Optional[int]:
    """jax.distributed.initialize's own heartbeat_timeout_seconds
    default, read from its signature (100 in jax 0.9)."""
    import inspect

    try:
        p = inspect.signature(jax.distributed.initialize).parameters
        return p["heartbeat_timeout_seconds"].default
    except (KeyError, ValueError, TypeError):
        return None


def _normalize_multihost(coordinator, num_processes, process_id) -> tuple:
    """Apply the env-var defaults so equivalent settings compare equal
    regardless of whether they came explicit or from the environment."""
    import os

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = os.environ["JAX_NUM_PROCESSES"]
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = os.environ["JAX_PROCESS_ID"]
    return (coordinator,
            None if num_processes is None else int(num_processes),
            None if process_id is None else int(process_id))


def ensure_multihost(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     heartbeat_timeout_s: Optional[int] = None) -> None:
    """Idempotent init_multihost: jax.distributed.initialize raises on a
    second call, but a process may legitimately build several successive
    Contexts (stop() then a new one) against the SAME global mesh. Asking
    for a different rendezvous than the one this process already joined
    cannot be honored and must fail loudly, not be masked."""
    if _multihost_settings is not None:
        requested = _normalize_multihost(coordinator, num_processes,
                                         process_id)
        if requested != _multihost_settings:
            from vega_tpu.errors import VegaError

            raise VegaError(
                "this process already joined a jax.distributed mesh with "
                f"settings {_multihost_settings}; a Context requesting "
                f"{requested} cannot re-rendezvous (jax.distributed "
                "initializes once per process)"
            )
        if heartbeat_timeout_s is not None \
                and heartbeat_timeout_s != _multihost_heartbeat_s:
            from vega_tpu.errors import VegaError

            raise VegaError(
                "this process already joined its jax.distributed mesh "
                f"with heartbeat_timeout_s={_multihost_heartbeat_s}; "
                f"requesting {heartbeat_timeout_s} cannot be honored "
                "(the coordination service is configured once per "
                "process)"
            )
        return
    init_multihost(coordinator, num_processes, process_id,
                   heartbeat_timeout_s=heartbeat_timeout_s)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """Build a 1-D mesh over the first n devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def default_mesh() -> Mesh:
    global _default_mesh
    with _lock:
        if _default_mesh is None:
            _default_mesh = make_mesh()
        return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    with _lock:
        _default_mesh = mesh


def shard_spec(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the mesh axis (axis 0 of every column)."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _identity_outputs(*xs):
    return xs


# One replicate-gather program per mesh: jit wrappers own their dispatch
# caches, so minting a fresh wrapper per host_get would re-trace every
# fetch. Keyed by Mesh (hashable); bounded — a process holds O(1) meshes.
_gather_jit_cache: dict = {}


def host_get(tree):
    """Multiprocess-safe jax.device_get over a pytree — ONE transfer.

    Pure-numpy trees (host-tier _HostMeshStub blocks on worker processes)
    pass straight through WITHOUT touching the jax backend: device init
    can hang on a wedged TPU tunnel, and host numpy must stay readable
    regardless (CLAUDE.md environment quirks). Single-process trees are
    exactly jax.device_get. Multi-process (jax.distributed global mesh):
    non-fully-addressable leaves cannot be fetched directly; all of them
    are replicated in ONE jitted identity program (an XLA all-gather —
    every process dispatches the same program, SPMD-safe) and then read
    locally. Drivers on every process therefore observe identical
    counts/flags and keep making identical dispatch decisions, which is
    what keeps the multi-controller model coherent."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not any(isinstance(x, jax.Array) for x in leaves):
        # vegalint: ignore[VG016] — numpy passthrough: no device touched
        return jax.device_get(tree)  # numpy passthrough, backend-free
    if jax.process_count() > 1:
        by_mesh: dict = {}
        for i, x in enumerate(leaves):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                by_mesh.setdefault(x.sharding.mesh, []).append(i)
        for m, idx in by_mesh.items():
            prog = _gather_jit_cache.get(m)
            if prog is None:
                prog = jax.jit(_identity_outputs,
                               out_shardings=NamedSharding(m, P()))
                _gather_jit_cache[m] = prog
            with device_door():
                gathered = prog(*[leaves[i] for i in idx])
            for i, g in zip(idx, gathered):
                leaves[i] = g  # fully replicated: locally readable
    # The dense tier's stage-launch transfer itself: DenseRDD.splits
    # materializes on the per-job drive thread BY DESIGN (one SPMD
    # program per stage), so the round trip is that job's own work,
    # bounded by device compute and the bench watchdog — it cannot park
    # other tenants' scheduling.
    with device_door():
        # vegalint: ignore[VG016] — stage-launch transfer on the job's own drive thread (see above)
        return jax.tree_util.tree_unflatten(treedef, jax.device_get(leaves))


def host_put(value, spec: NamedSharding) -> jax.Array:
    """Multiprocess-safe jax.device_put of a host value every process
    holds identically (the SPMD driver model guarantees it): each process
    materializes only its addressable shards via make_array_from_callback;
    single-process falls through to plain device_put."""
    if jax.process_count() == 1:
        return jax.device_put(value, spec)
    arr = np.asarray(value)
    return jax.make_array_from_callback(arr.shape, spec,
                                        lambda idx: arr[idx])
