"""Device mesh management.

The reference's parallel substrate is an executor fleet reached over TCP
(SURVEY.md §2.5); vega_tpu's is a jax.sharding.Mesh. One axis, "shards",
spans every addressable device: dense-RDD partitions map 1:1 onto mesh
shards, shuffles ride all_to_all over ICI, and multi-host meshes come from
jax.distributed (the DCN analogue of the reference's multi-host deployment,
context.rs:209-303).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shards"

_lock = threading.Lock()
_default_mesh: Optional[Mesh] = None


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Join a multi-host device mesh via jax.distributed.

    The DCN analogue of the reference's multi-host deployment
    (context.rs:209-303 ssh bootstrap): every host runs the same program,
    jax.distributed glues their local chips into one global device set, and
    default_mesh() then spans all of them — collectives ride ICI within a
    slice and DCN across slices, inserted by XLA from the same shard_map
    programs. No code changes anywhere else: exchanges are mesh-size
    agnostic.

    Args default from the standard env vars (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID) or the TPU metadata service.
    """
    import os

    kwargs = {}
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes
            if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"]
        )
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None
            else os.environ["JAX_PROCESS_ID"]
        )
    jax.distributed.initialize(**kwargs)
    set_default_mesh(None)  # rebuild over the now-global device set


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """Build a 1-D mesh over the first n devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def default_mesh() -> Mesh:
    global _default_mesh
    with _lock:
        if _default_mesh is None:
            _default_mesh = make_mesh()
        return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    with _lock:
        _default_mesh = mesh


def shard_spec(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the mesh axis (axis 0 of every column)."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
