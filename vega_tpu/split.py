"""Partition descriptors (reference: src/split.rs:8-13).

A Split is an index plus an optional per-RDD payload (e.g. the data slice a
ParallelCollection split carries, reference:
src/rdd/parallel_collection_rdd.rs:30-56, or the (s1, s2) pair of a cartesian
split, src/rdd/cartesian_rdd.rs:86-103).
"""

from __future__ import annotations

from typing import Any


class Split:
    __slots__ = ("index", "payload")

    def __init__(self, index: int, payload: Any = None):
        self.index = index
        self.payload = payload

    def __repr__(self):
        return f"Split({self.index})"

    def __eq__(self, other):
        return isinstance(other, Split) and other.index == self.index

    def __hash__(self):
        return hash(("Split", self.index))
