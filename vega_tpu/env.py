"""Process-global environment: config + lazily-started services.

Reference: src/env.rs. The reference holds a lazy singleton bundling the tokio
runtime, map-output tracker, shuffle manager and cache (env.rs:38-96) plus a
Configuration read from VEGA_* env vars / a worker-local config.toml
(env.rs:131-293). vega_tpu keeps the same shape: `Env.get()` is the process
singleton; configuration comes from VEGA_TPU_* env vars with the same field
set (deployment_mode, local_ip, local_dir, log_level, shuffle port, ...).
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import os
import tempfile
import uuid
from typing import Optional

from vega_tpu.lint.sync_witness import assert_role, named_lock

log = logging.getLogger("vega_tpu")


class DeploymentMode(enum.Enum):
    """Reference: src/env.rs:146-149."""

    LOCAL = "local"
    DISTRIBUTED = "distributed"


@dataclasses.dataclass
class Configuration:
    """Reference: src/env.rs:162-272 (field-for-field, TPU additions at end)."""

    deployment_mode: DeploymentMode = DeploymentMode.LOCAL
    local_ip: str = "127.0.0.1"
    local_dir: str = dataclasses.field(
        default_factory=lambda: os.path.join(tempfile.gettempdir(), "vega-tpu")
    )
    log_level: str = "WARNING"
    log_cleanup: bool = True
    shuffle_service_port: Optional[int] = None
    slave_deployment: bool = False
    slave_port: Optional[int] = None
    # --- vega_tpu additions ---
    # Worker threads for the local scheduler's task pool.
    num_workers: int = dataclasses.field(
        default_factory=lambda: os.cpu_count() or 4
    )
    # Initial executor count for distributed mode (None -> hosts file if
    # configured, else 2 — the backend's historical default). The elastic
    # plane starts here and moves the fleet between
    # elastic_min/max_executors.
    num_executors: Optional[int] = None
    # Round-trip tasks through serialization even in local mode, like the
    # reference does (local_scheduler.rs:345-351): catches unserializable
    # closures early. Costs wall time; disable for pure-local perf runs.
    serialize_tasks_locally: bool = False
    # Cache capacity in bytes for BoundedMemoryCache (reference hardcodes
    # 2000MB at cache.rs:29; we make it configurable and actually evict).
    cache_capacity_bytes: int = 2_000 * 1024 * 1024
    # Tiered block store (vega_tpu/store): spill directory root. None ->
    # <local_dir>/session-<id>/spill, i.e. rooted at VEGA_TPU_LOCAL_DIR —
    # per-process (so per-executor) and removed on shutdown.
    spill_dir: Optional[str] = None
    # Shuffle store memory budget: total in-RAM bucket bytes before the
    # oldest buckets spill to disk (the reference pins every bucket in
    # process memory forever — env.rs:19; large shuffles simply OOM'd).
    shuffle_memory_budget: int = 1 << 30
    # Individual buckets larger than this go straight to disk.
    shuffle_spill_threshold: int = 64 * 1024 * 1024
    # Scheduler timeouts (reference: distributed_scheduler.rs:87-88).
    resubmit_timeout_s: float = 2.0
    poll_timeout_s: float = 0.05
    # Max task retries before failing the job (reference plumbs max_failures
    # but never enforces it, local_scheduler.rs:29,57 — we enforce it).
    max_failures: int = 4
    # Multi-job task arbitration (scheduler/jobserver.py): "fifo"
    # dispatches ready tasks of all concurrent jobs in global submission
    # order (the reference's effective behavior — one long job's backlog
    # gates every later job); "fair" shares backend slots across pools by
    # weight, and across jobs within a pool by fewest-running-first, so
    # short interactive jobs are not starved by a long batch job.
    # Switchable at runtime via ctx.job_server.set_scheduler_mode(...).
    scheduler_mode: str = "fifo"
    # Locality-aware task placement (distributed mode). > 0 turns the
    # plane ON: the DAG scheduler computes reduce-side preferred
    # locations (push-plan pre-merge owner / pull-plan biggest-bytes
    # server) and _pick_executor scores candidates
    # PROCESS_LOCAL > HOST_LOCAL > ANY, breaking ties by fewest in-flight
    # tasks; a task whose only preferred executors are TEMPORARILY down
    # (a respawn in flight or budgeted) waits up to this many seconds
    # before settling for a worse tier — permanently dead, blacklisted or
    # speculation-excluded preferred executors demote immediately, so the
    # wait can never starve a task. 0 turns the whole plane off and
    # reproduces the legacy round-robin + first-match placement.
    locality_wait_s: float = 0.3
    # --- executor fault tolerance (distributed mode) ---
    # Worker -> driver heartbeat period. Must be well under
    # executor_liveness_timeout_s or healthy workers get reaped.
    heartbeat_interval_s: float = 2.0
    # A registered executor whose last heartbeat is older than this is
    # declared lost: its map outputs are unregistered (generation bump),
    # its in-flight dispatches are failed over, and ExecutorLost is
    # emitted. Detects wedged-but-alive workers, not just dead sockets.
    executor_liveness_timeout_s: float = 30.0
    # Reaper sweep period (driver-side liveness thread).
    executor_reap_interval_s: float = 5.0
    # Dead local/ssh workers are respawned up to this many times per slot
    # with exponential backoff; 0 disables respawn.
    executor_max_restarts: int = 3
    # Base respawn delay; attempt k waits backoff * 2**k.
    executor_restart_backoff_s: float = 1.0
    # Executors accumulating this many dispatch failures are skipped by
    # _pick_executor while any non-blacklisted executor is alive (repeat
    # offenders stop eating task attempts).
    executor_blacklist_threshold: int = 5
    # Transient shuffle-fetch retry: a dropped connection is retried in
    # place this many times (with linear backoff fetch_retry_interval_s)
    # before escalating to FetchFailedError and a stage resubmission.
    # A server answering "missing" escalates immediately (not transient).
    fetch_retries: int = 3
    fetch_retry_interval_s: float = 0.2
    # Pipelined shuffle fetch (shuffle/fetcher.py): batched `get_many`
    # requests — ONE round trip per (reducer, server) instead of one per
    # bucket — answered as a stream the reducer merges while later
    # buckets are still on the wire. 0/false falls back to the per-bucket
    # `get` protocol (same pipeline, M round trips).
    fetch_batch_enabled: bool = True
    # Bound on the fetch pipeline's bucket queue: at most this many
    # fetched-but-unmerged buckets are resident per reduce task (producer
    # threads block past it — backpressure IS the reducer's peak-memory
    # bound; the old path materialized the entire List[bytes]).
    fetch_queue_buckets: int = 32
    # --- task dispatch plane ---
    # Deduplicated dispatch: tasks ship as a tiny header plus a
    # stage-level binary (the shared (rdd, func | shuffle_dep) closure,
    # cloudpickled once per stage, content-hashed, sent to each executor
    # on first use only — a worker lacking the hash answers `need_binary`
    # and gets it re-shipped inline, so correctness never depends on
    # driver bookkeeping). Results return with protocol-5 out-of-band
    # buffers (zero-copy numpy). 0/false keeps the legacy
    # one-envelope-per-task protocol live (A/B and fallback; the
    # reference's only shape, serialized_data.capnp).
    task_binary_dedup: bool = True
    # Bound on the executor-side LRU of *deserialized* stage binaries
    # (one lineage unpickle per stage per executor, not per task). An
    # evicted hash recovers via the need_binary re-ship.
    task_binary_cache_entries: int = 32
    # Dense-tier shuffle collective. "auto" (default) routes every
    # exchange launch through the collective-aware planner
    # (tpu/exchange_plan.py): one-shot "all_to_all" when its estimated
    # per-shard transient peak fits dense_hbm_budget, the blocked
    # "staged" program (K sub-rounds of peer groups over shifted
    # ppermutes, K chosen so the estimate fits) when it doesn't, "ring"
    # (single bounded buffer, n-1 rounds — the minimum possible peak)
    # when no larger group fits. Explicit "all_to_all" / "ring" /
    # "staged" force that program per run. See tpu/ring.py and
    # tpu/exchange_plan.py.
    dense_exchange: str = "auto"
    # Cluster membership file for distributed mode (reference: ~/hosts.conf,
    # src/hosts.rs); None -> VEGA_TPU_HOSTS_FILE -> ~/hosts.conf -> local.
    hosts_file: Optional[str] = None
    # Speculative execution (straggler mitigation; the reference has none):
    # once a quorum of a stage's tasks has finished (speculation_quorum
    # fraction of its submitted tasks), a pending task that has run longer
    # than max(speculation_min_s, speculation_multiplier * median task
    # duration) gets ONE duplicate attempt launched — on a different,
    # non-blacklisted executor in distributed mode. First completion wins
    # (dedup by (stage_id, partition)); the loser is cancelled best-effort
    # via the `cancel_task` protocol message. NOTE: like task retries,
    # this gives at-least-once semantics for user side effects (for_each
    # etc.) — framework-owned writes (save_as_text_file, shuffle buckets)
    # are duplicate-safe.
    speculation_enabled: bool = False
    speculation_multiplier: float = 3.0
    speculation_min_s: float = 1.0
    # Fraction of a stage's tasks that must have COMPLETED before any of
    # its stragglers are eligible for speculation (the median is garbage
    # on two data points).
    speculation_quorum: float = 0.75
    # Replicated shuffle writes (the data-side redundancy of
    # arXiv:1802.03049): each map task's buckets are written to this many
    # executors' stores (1 = primary only). Reducers treat the extra
    # locations as failover targets — a dead or slow server's undelivered
    # buckets are re-requested from a replica mid-stream, with no stage
    # resubmission and no map recompute.
    shuffle_replication: int = 1
    # Shuffle plan (PR 8, Exoshuffle map-side push as a policy over the
    # existing store/fetch primitives — never a fork of the plane):
    #   "pull" (default) — the PR 4 pipeline: map outputs park locally,
    #     reducers batch-fetch them after the whole map stage registered.
    #   "push" — map tasks additionally push each finished bucket to its
    #     reducer's OWNING server (rotation over the live peer list);
    #     that server pre-merges mergeable buckets into the existing
    #     MergeState machinery as they arrive, and reducers start from
    #     ONE mostly-merged blob, pulling only the stragglers that never
    #     arrived — the shuffle barrier becomes a map/reduce pipeline.
    # Push is strictly additive: the local bucket row and its registered
    # locations are byte-identical to the pull plan, so any push failure
    # (dead peer, fleet churn, overflow) silently degrades to pull.
    shuffle_plan: str = "pull"
    # When > 0 and every bucket requested from a server has at least one
    # replica location, the batched get_many round runs under this socket
    # deadline with no in-place retries: a server unresponsive past it
    # fails over to the replicas instead of gating the reduce task on the
    # slowest source. 0 keeps the normal fetch_retries behavior.
    fetch_slow_server_s: float = 0.0
    # Coded shuffle (third redundancy-ladder leg, arXiv:1802.03049 via
    # shuffle/coding.py): "none" (default) | "xor" | "rs" | "rs(k,m)".
    # Map tasks ship each bucket row ONCE (compressed) to a parity
    # server, which folds rotation groups of up to `coding_group_k`
    # same-shuffle rows — at most one per origin server, so any single
    # server loss is decodable — into parity buckets: one XOR unit, or
    # `coding_parity_m` Reed–Solomon units (any ≤m losses decode). On a
    # dead server the fetch path RECONSTRUCTS missing buckets from the
    # surviving members plus parity instead of resubmitting the map
    # stage: replica-grade recovery at ~(1/group)× storage instead of
    # (k-1)×. Composes with shuffle_replication (replica failover is
    # tried first) and shuffle_plan=push; degradation ladder stays total
    # (coded -> replica -> FetchFailed -> resubmit).
    shuffle_coding: str = "none"
    coding_group_k: int = 4
    coding_parity_m: int = 1
    # Dense-tier HBM budget in bytes (per chip). Sources stream through
    # the mesh in chunks (tpu/stream.py) when estimated block bytes times
    # the exchange footprint factor (~6: operand + sorted copy + send
    # slots + received block) exceed this — i.e. resident execution is
    # kept only while block_bytes * 6 <= budget. Default 4 GiB:
    # conservative for a 16 GiB v5e chip once XLA workspace and a second
    # live block are accounted for.
    dense_hbm_budget: int = 4 << 30
    # reduce_by_key exchange plan: "fused_sort" = ONE multi-key
    # (bucket, key) lax.sort feeds the presorted combine AND a pregrouped
    # exchange; "sort_partition" = key-only lax.sort -> combine -> stable
    # counting partition by bucket (kernels.partition_by_bucket) — the
    # partition is cheap VPU work over the POST-combine rows, so it wins
    # when the combine shrinks data a lot (high key duplication) and the
    # sort dominates. "auto" (round-5 default) resolves per backend from
    # the measured evidence: sort_partition on CPU (won the A/B at both
    # 2M and 5M bench shapes, 10-20% faster warm end-to-end —
    # docs/BENCH_NOTES.md round 5), fused_sort on TPU until the queued
    # on-chip A/B (benchmarks/tpu_jobs/02_plan_ab.sh) decides: the only
    # hardware number ever captured used fused_sort, and the headline
    # bench must not gamble on a plan with no on-chip measurement.
    dense_rbk_plan: str = "auto"
    # Key-sort implementation inside exchange programs: "xla" = lax.sort
    # comparator network; "packed" = (key, perm) packed into one 63-bit
    # word so the sort is XLA's fast SINGLE-operand case (its
    # multi-operand sort is 4-8x slower at bench shapes on CPU);
    # "radix" / "radix4" = LSD radix over orderable-uint32 words (8-bit
    # digits / 4 passes per word, or 4-bit digits / 8 passes with 16x
    # less per-tile kernel unroll; Pallas-streamed histogram + rank
    # kernels on TPU) for int32/float32/wide-int64 keys — other dtypes
    # keep lax.sort. "auto" (round-5 default) resolves per backend:
    # packed on CPU (measured 3.8x on the dominant reduce sort at the 5M
    # bench shape — docs/BENCH_NOTES.md round 5), xla on TPU until the
    # queued on-chip A/B (benchmarks/tpu_jobs/03_radix_ab.sh, which
    # also measures packed) decides.
    dense_sort_impl: str = "auto"
    # --- elastic serving plane (scheduler/elastic.py; distributed mode) ---
    # Master switch for the autoscaler control loop: the driver samples
    # load signals (arbiter queue depth, per-pool backlog, per-executor
    # in-flight watermarks) every elastic_decision_interval_s and
    # spawns/decommissions executors between the min/max bounds. Off by
    # default: the fleet stays exactly as spawned (the reference sizes
    # it once at context.rs launch time and never revisits).
    elastic_enabled: bool = False
    # Fleet bounds the autoscaler may move between. The initial fleet is
    # num_executors/hosts as before; scale-down never drains below min,
    # scale-up never spawns past max.
    elastic_min_executors: int = 1
    elastic_max_executors: int = 8
    # Scale UP when (running + queued tasks) per live executor SLOT
    # (num_workers slots per executor) holds above this watermark for a
    # full decision interval. 1.0 = grow as soon as the fleet is more
    # than fully subscribed for an interval.
    elastic_scale_up_threshold: float = 2.0
    # Scale DOWN (graceful decommission of one executor per decision)
    # when fleet occupancy — running tasks / total slots — holds BELOW
    # this fraction for a full decision interval with nothing queued.
    elastic_scale_down_threshold: float = 0.25
    # Sampling period of the control loop; a watermark must hold for one
    # full interval (two consecutive samples) before the loop acts, so a
    # single bursty sample never flaps the fleet.
    elastic_decision_interval_s: float = 1.0
    # Graceful decommission: how long the victim may take to drain its
    # in-flight tasks before the drain escalates to the PR 2
    # executor-lost path (socket teardown, output unregistration, task
    # failover) instead of waiting forever on a wedged victim.
    decommission_timeout_s: float = 10.0
    # Admission control (scheduler/jobserver.py): maximum jobs a pool may
    # have in flight (submitted, not yet settled) before submit_job stops
    # admitting more — the bound that replaces unbounded queueing at the
    # multi-tenant front door. 0 = unbounded (legacy behavior).
    # Per-pool overrides via ctx.set_pool(..., max_queued=N).
    pool_max_queued: int = 0
    # What a full pool does to the submitter: "reject" raises the typed
    # JobRejectedError immediately; "block" parks the submitting thread
    # until a job of that pool settles (backpressure).
    admission_mode: str = "reject"
    # Dispatch-failure blacklists age out: an executor whose last
    # transport failure is older than this many seconds has its
    # consecutive-failure count forgiven, so a recovered-but-once-flaky
    # executor rejoins _pick_executor rotation instead of staying
    # advisory-deprioritized forever. 0 disables decay (legacy).
    blacklist_decay_s: float = 60.0
    # Speculative dense-key table plan for warm named reduces (scatter
    # table + psum + hash-mask compact; dense_rdd.py). "auto" (default)
    # activates it on CPU only — measured 3-4x on the bench reduce there
    # — and keeps TPU on the standard exchange until the queued on-chip
    # A/B (benchmarks/tpu_jobs/02_plan_ab.sh table leg) decides: the
    # only hardware number ever captured ran the exchange path, and the
    # headline bench must not gamble on an unmeasured plan. "on"/"off"
    # force it per run (the A/B job sets "on").
    dense_table_plan: str = "auto"
    # --- device-tier string columns (tpu/dict_encoding.py) ---
    # Master switch for dictionary-encoded string columns on the device
    # tier: string columns become int32 code columns plus a sorted
    # dictionary sidecar on the Block (codes ARE rank codes, so order
    # ops need no extra pass), unified across blocks before keyed binary
    # ops and decoded only at the collect boundary. False keeps the
    # pre-PR-20 behavior — string data raises at the block boundary and
    # the caller degrades to the host tier (the forced-host leg of
    # benchmarks/strings_ab.py sets this).
    dense_dict_enabled: bool = True
    # Starting capacity (entries) of the padded dictionary tables staged
    # into the cross-block unification remap program. A REAL capacity,
    # same contract as exchange capacities: a code at or past the staged
    # table sets the device overflow flag and the driver retries with
    # doubled capacity (tests shrink this to exercise the retry path).
    dense_dict_capacity: int = 65536
    # --- micro-batch streaming (vega_tpu/streaming/) ---
    # Discretization interval: how often the streaming context snapshots
    # receiver blocks into one micro-batch and submits its output jobs.
    stream_batch_interval_s: float = 0.5
    # Receivers cut a block (land it in the tiered store and queue it for
    # the next batch) at this many records; a batch tick also flushes the
    # partial block so low-rate streams still make progress.
    stream_block_max_records: int = 10_000
    # Backpressure bound: maximum receiver blocks landed but not yet
    # consumed by a completed batch. At the bound the receiver applies
    # stream_backpressure_mode instead of queueing without limit.
    stream_queue_max_blocks: int = 64
    # What a full block queue does to ingest: "block" parks the receiver
    # until a batch drains blocks (lossless; the socket source's peer
    # sees TCP backpressure); "shed" drops the newest block while still
    # advancing source offsets (lossy by design — counted and surfaced,
    # mirroring jobserver admission_mode reject/block).
    stream_backpressure_mode: str = "block"
    # Fair-scheduler pool streaming batches are submitted into, and its
    # weight vs the default batch pool (set via ctx.set_pool at streaming
    # start) — the isolation that keeps a heavy batch tenant from
    # starving the stream.
    stream_pool: str = "streaming"
    stream_pool_weight: int = 4
    # StorageLevel for receiver blocks in the tiered store. The default
    # keeps blocks replayable across memory pressure (eviction demotes to
    # disk instead of dropping — a failed batch must recompute from
    # stored blocks, never from the wire).
    stream_storage_level: str = "memory_and_disk"
    # Socket source read timeout: every recv on the streaming socket
    # carries this bound (VG012/VG015 — no unbounded waits), so a silent
    # peer never wedges the receiver thread past it.
    stream_socket_timeout_s: float = 5.0
    # Where stateful streams write their (batch_id, offsets, state)
    # commit records + checkpointed state parts. Empty = under the
    # session work dir (wiped with the session; set it to survive a
    # driver restart).
    stream_checkpoint_dir: str = ""

    @staticmethod
    def from_environ(environ=None) -> "Configuration":
        env = os.environ if environ is None else environ
        cfg = Configuration()
        pref = "VEGA_TPU_"
        if env.get(pref + "DEPLOYMENT_MODE"):
            cfg.deployment_mode = DeploymentMode(env[pref + "DEPLOYMENT_MODE"])
        for name in ("LOCAL_IP", "LOCAL_DIR", "LOG_LEVEL", "DENSE_EXCHANGE",
                     "DENSE_RBK_PLAN", "DENSE_SORT_IMPL",
                     "DENSE_TABLE_PLAN", "HOSTS_FILE", "SPILL_DIR",
                     "SCHEDULER_MODE", "SHUFFLE_PLAN", "SHUFFLE_CODING",
                     "ADMISSION_MODE",
                     "STREAM_BACKPRESSURE_MODE", "STREAM_POOL",
                     "STREAM_STORAGE_LEVEL", "STREAM_CHECKPOINT_DIR"):
            if env.get(pref + name):
                setattr(cfg, name.lower(), env[pref + name])
        for name in ("SHUFFLE_SERVICE_PORT", "SLAVE_PORT", "NUM_WORKERS",
                     "NUM_EXECUTORS",
                     "CACHE_CAPACITY_BYTES", "MAX_FAILURES",
                     "DENSE_HBM_BUDGET", "SHUFFLE_MEMORY_BUDGET",
                     "SHUFFLE_SPILL_THRESHOLD", "DENSE_DICT_CAPACITY",
                     "EXECUTOR_MAX_RESTARTS",
                     "EXECUTOR_BLACKLIST_THRESHOLD", "FETCH_RETRIES",
                     "FETCH_QUEUE_BUCKETS", "TASK_BINARY_CACHE_ENTRIES",
                     "SHUFFLE_REPLICATION", "CODING_GROUP_K",
                     "CODING_PARITY_M", "ELASTIC_MIN_EXECUTORS",
                     "ELASTIC_MAX_EXECUTORS", "POOL_MAX_QUEUED",
                     "STREAM_BLOCK_MAX_RECORDS", "STREAM_QUEUE_MAX_BLOCKS",
                     "STREAM_POOL_WEIGHT"):
            if env.get(pref + name):
                setattr(cfg, name.lower(), int(env[pref + name]))
        for name in ("LOG_CLEANUP", "SLAVE_DEPLOYMENT", "SERIALIZE_TASKS_LOCALLY",
                     "SPECULATION_ENABLED", "FETCH_BATCH_ENABLED",
                     "TASK_BINARY_DEDUP", "ELASTIC_ENABLED",
                     "DENSE_DICT_ENABLED"):
            if env.get(pref + name):
                setattr(cfg, name.lower(), env[pref + name].lower() in ("1", "true"))
        for name in ("RESUBMIT_TIMEOUT_S", "POLL_TIMEOUT_S",
                     "SPECULATION_MULTIPLIER", "SPECULATION_MIN_S",
                     "SPECULATION_QUORUM",
                     "HEARTBEAT_INTERVAL_S", "EXECUTOR_LIVENESS_TIMEOUT_S",
                     "EXECUTOR_REAP_INTERVAL_S", "EXECUTOR_RESTART_BACKOFF_S",
                     "FETCH_RETRY_INTERVAL_S", "FETCH_SLOW_SERVER_S",
                     "LOCALITY_WAIT_S", "ELASTIC_SCALE_UP_THRESHOLD",
                     "ELASTIC_SCALE_DOWN_THRESHOLD",
                     "ELASTIC_DECISION_INTERVAL_S", "DECOMMISSION_TIMEOUT_S",
                     "BLACKLIST_DECAY_S", "STREAM_BATCH_INTERVAL_S",
                     "STREAM_SOCKET_TIMEOUT_S"):
            if env.get(pref + name):
                setattr(cfg, name.lower(), float(env[pref + name]))
        return cfg


def normalize_log_level(level) -> int:
    """'info'/'INFO'/20 -> 20; invalid values fall back to WARNING instead
    of crashing startup."""
    if isinstance(level, int):
        return level
    resolved = logging.getLevelName(str(level).upper())
    return resolved if isinstance(resolved, int) else logging.WARNING


def attach_session_logger(env: "Env", role: str):
    """Per-session log file (reference: simplelog combined file+terminal
    logger — ns-driver.log / ns-executor.log, context.rs:542-564). Returns
    the handler (caller owns detach/cleanup) or None when the directory is
    unwritable. Never *raises* the logger threshold: an application that
    configured more verbose logging keeps it."""
    try:
        path = os.path.join(env.work_dir(), f"{role}.log")
        handler = logging.FileHandler(path)
    except OSError:
        return None
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s %(message)s"
    ))
    level = normalize_log_level(env.conf.log_level)
    handler.setLevel(level)
    log.addHandler(handler)
    if level < log.getEffectiveLevel():
        log.setLevel(level)
    return handler


def detach_session_logger(handler, cleanup: bool) -> None:
    if handler is None:
        return
    log.removeHandler(handler)
    path = handler.baseFilename
    handler.close()
    if cleanup:
        try:
            os.unlink(path)
        except OSError:
            pass


class Env:
    """Lazy process singleton (reference: src/env.rs:38-96).

    Bundles the shuffle store, map-output tracker client/server, cache, and
    cache tracker. Services start on first access, exactly like the
    reference's once_cell pattern.
    """

    _instance: Optional["Env"] = None
    _lock = named_lock("env.Env._lock")

    def __init__(self, conf: Optional[Configuration] = None, is_driver: bool = True):
        from vega_tpu.cache import BoundedMemoryCache
        from vega_tpu.shuffle.store import ShuffleStore
        from vega_tpu.store import DiskStore, TieredCache

        self.conf = conf or Configuration.from_environ()
        self.is_driver = is_driver
        self.session_id = uuid.uuid4().hex[:12]
        # Spill root (paths only — DiskStore mkdirs lazily on first write,
        # so constructing an Env touches no filesystem). Always suffixed
        # with the per-process session id, INCLUDING under an explicit
        # VEGA_TPU_SPILL_DIR: driver and executors share that env var, and
        # a bare shared root would let one process's shutdown rmtree
        # delete every other live executor's disk-resident blocks.
        base = self.conf.spill_dir or os.path.join(self.conf.local_dir,
                                                   "spill")
        spill_root = os.path.join(base, f"session-{self.session_id}")
        self.shuffle_store = ShuffleStore(
            spill_dir=os.path.join(spill_root, "shuffle"),
            spill_threshold=self.conf.shuffle_spill_threshold,
            memory_budget=self.conf.shuffle_memory_budget,
        )
        self.cache = TieredCache(
            BoundedMemoryCache(self.conf.cache_capacity_bytes),
            DiskStore(os.path.join(spill_root, "cache")),
        )
        self.map_output_tracker = None  # set by Context/Executor at startup
        self.cache_tracker = None
        self.shuffle_server = None  # distributed mode only
        self.executor_id: Optional[str] = None
        # Set by the Context to LiveListenerBus.post (driver-side): the
        # shuffle fetcher posts ShuffleFetchCompleted per reduce stream.
        # Executors keep process-local counters only (fetcher.stats).
        self.fetch_event_sink = None

    @classmethod
    def get(cls) -> "Env":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = Env()
        return cls._instance

    @classmethod
    def reset(cls, conf: Optional[Configuration] = None, is_driver: bool = True) -> "Env":
        """Replace the singleton (tests / worker bootstrap)."""
        # Worker bootstrap calls this on the worker process's MAIN thread
        # (un-noted -> passes); a task-handler or receiver thread doing it
        # would corrupt every concurrent task's view of the Env.
        assert_role()
        with cls._lock:
            cls._instance = Env(conf, is_driver)
        return cls._instance

    def work_dir(self) -> str:
        d = os.path.join(self.conf.local_dir, f"session-{self.session_id}")
        os.makedirs(d, exist_ok=True)
        return d
