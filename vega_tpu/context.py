"""Driver entry point (reference: src/context.rs).

Owns RDD/shuffle id counters (context.rs:398-404), RDD constructors
(make_rdd/parallelize/range/read_source/union, context.rs:406-455,537-539) and
job runners (run_job/run_approximate_job, context.rs:457-524). Deployment mode
selects the task backend: local thread pool, distributed executor fleet
(vega_tpu/distributed), with the device tier layered on top for numeric RDDs
(vega_tpu/tpu).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from vega_tpu.cache_tracker import CacheTracker
from vega_tpu.env import Configuration, DeploymentMode, Env
from vega_tpu.errors import VegaError
from vega_tpu.map_output_tracker import MapOutputTracker
from vega_tpu.partial.partial_result import PartialResult
from vega_tpu.rdd.base import RDD
from vega_tpu.scheduler.dag import DAGScheduler
from vega_tpu.scheduler.events import LiveListenerBus, MetricsListener
from vega_tpu.scheduler.jobserver import JobFuture, JobServer
from vega_tpu.scheduler.local_backend import LocalBackend

log = logging.getLogger("vega_tpu")


import contextlib
from vega_tpu.lint.sync_witness import assert_role, named_lock


@contextlib.contextmanager
def _profile_trace(log_dir: str):
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


_active_context_lock = named_lock("context._active_context_lock")
_active_context: Optional["Context"] = None


class Context:
    def __init__(self, mode: str | DeploymentMode = "local",
                 conf: Optional[Configuration] = None,
                 multihost: Optional[dict] = None, **conf_overrides):
        global _active_context
        self._stopped = False
        # Claim the active slot atomically with the liveness check (a
        # check-then-register race would let two threads both pass and the
        # second Env.reset clobber the first context's shuffles — the
        # exact corruption this guard exists to prevent).
        with _active_context_lock:
            if _active_context is not None and not _active_context._stopped:
                raise VegaError(
                    "a Context is already active in this process — the Env "
                    "(shuffle store, trackers) is a process singleton like "
                    "the reference's (env.rs:38-40), so a second Context "
                    "would silently break the first one's shuffles. Call "
                    ".stop() on it — reachable via Context.active() if the "
                    "variable was lost — or use `with Context(...)`."
                )
            _active_context = self
        try:
            if isinstance(mode, str):
                mode = DeploymentMode(mode)
            conf = conf or Configuration.from_environ()
            conf.deployment_mode = mode
            for key, value in conf_overrides.items():
                if not hasattr(conf, key):
                    raise TypeError(f"unknown configuration field: {key}")
                setattr(conf, key, value)
            self.conf = conf
            if multihost is not None:
                # Join the jax.distributed global mesh BEFORE any backend
                # touch: every process runs this same driver program and
                # the dense tier then executes SPMD over all processes'
                # devices (the DCN analogue of the reference's multi-host
                # executor fleet, context.rs:209-303). Keys: coordinator,
                # num_processes, process_id (each defaultable from the
                # JAX_* env vars — see tpu/mesh.init_multihost).
                from vega_tpu.tpu import mesh as _mesh_lib

                _mesh_lib.ensure_multihost(**multihost)
            env = Env.reset(conf, is_driver=True)
            env.map_output_tracker = MapOutputTracker()
            env.cache_tracker = CacheTracker()
            self._log_handler = None

            self._next_rdd_id = itertools.count(0)
            self._next_shuffle_id = itertools.count(0)

            self.bus = LiveListenerBus()
            self.metrics = MetricsListener()
            self.bus.add_listener(self.metrics)
            self.bus.start()
            # Storage tiering observability: the tiered cache and shuffle
            # store post BlockSpilled/BlockPromoted onto the scheduler
            # event bus (executors have no bus; they keep counters that
            # surface through the shuffle server's `status`).
            env.cache.event_sink = self.bus.post
            env.shuffle_store.event_sink = self.bus.post
            # Fetch-pipeline observability: driver-side reduce tasks post
            # ShuffleFetchCompleted per stream (round trips / bytes /
            # overlap); executor fetches keep fetcher-local counters.
            env.fetch_event_sink = self.bus.post

            if mode is DeploymentMode.LOCAL:
                self._backend = LocalBackend()
            else:
                from vega_tpu.distributed.backend import DistributedBackend

                self._backend = DistributedBackend(conf)
            self.scheduler = DAGScheduler(self._backend, self.bus)
            # Multi-job front door (scheduler/jobserver.py): every action
            # — blocking or async — routes through it, so fair-scheduling
            # pools, quotas, and cancellation apply uniformly. Jobs run
            # concurrently, each on its own event-loop thread.
            self.job_server = JobServer(self.scheduler, conf)
            # Elastic serving plane (scheduler/elastic.py): the
            # autoscaler exists for any fleet-shaped backend so manual
            # decommission and fleet_status work even with the control
            # loop off; the loop itself only runs under elastic_enabled.
            self.elastic = None
            if hasattr(self._backend, "fleet_snapshot"):
                from vega_tpu.scheduler.elastic import ElasticController

                self.elastic = ElasticController(
                    self._backend, self.job_server.arbiter,
                    self.scheduler, conf, self.bus)
                if getattr(conf, "elastic_enabled", False):
                    self.elastic.start()
            # Thread-local submission properties (Spark's
            # setLocalProperty): "pool" selects the scheduling pool for
            # jobs submitted from this thread.
            self._local_props = threading.local()
            # Lazily created micro-batch streaming plane
            # (vega_tpu/streaming/): one per Context, like the job server.
            self._streaming = None
            # Attach last: a failed backend init must not leak a file
            # handler on the process-global logger.
            from vega_tpu.env import attach_session_logger

            self._prev_logger_level = log.level
            self._log_handler = attach_session_logger(env, "driver")
        except BaseException:
            with _active_context_lock:
                if _active_context is self:
                    _active_context = None
            raise

    @staticmethod
    def active() -> Optional["Context"]:
        """The live Context of this process, if any — the recovery handle
        when the creating variable was lost (Context.active().stop())."""
        with _active_context_lock:
            return _active_context

    # ------------------------------------------------------------------ ids
    def new_rdd_id(self) -> int:
        """Reference: context.rs:398-400."""
        return next(self._next_rdd_id)

    def new_shuffle_id(self) -> int:
        """Reference: context.rs:402-404."""
        return next(self._next_shuffle_id)

    # ----------------------------------------------------------- constructors
    def parallelize(self, data: Sequence, num_slices: Optional[int] = None) -> RDD:
        """Reference: context.rs:406-420 (make_rdd/parallelize)."""
        from vega_tpu.rdd.narrow import ParallelCollectionRDD

        n = num_slices or self.default_parallelism
        return ParallelCollectionRDD(self, data, n)

    make_rdd = parallelize

    def range(self, start: int, stop: Optional[int] = None, step: int = 1,
              num_slices: Optional[int] = None) -> RDD:
        """Reference: context.rs:422-442. Lazy: slices of a Python range are
        ranges, so no materialization happens until compute."""
        if stop is None:
            start, stop = 0, start
        return self.parallelize(range(start, stop, step), num_slices)

    def union(self, rdds: List[RDD]) -> RDD:
        """Reference: context.rs:537-539."""
        from vega_tpu.rdd.union import UnionRDD

        return UnionRDD(self, rdds)

    def empty_rdd(self) -> RDD:
        return self.parallelize([], 1)

    def read_source(self, config, decoder: Optional[Callable] = None) -> RDD:
        """Reference: context.rs:445-455 + src/io/local_file_reader.rs."""
        rdd = config.make_reader(self)
        if decoder is not None:
            rdd = rdd.map(decoder)
        return rdd

    def text_file(self, path: str, num_partitions: Optional[int] = None) -> RDD:
        from vega_tpu.io.readers import TextFileReaderConfig

        return self.read_source(
            TextFileReaderConfig(path, num_partitions or self.default_parallelism)
        )

    def whole_text_files(self, path: str) -> RDD:
        from vega_tpu.io.readers import WholeFileReaderConfig

        return self.read_source(WholeFileReaderConfig(path))

    def parquet_file(self, path: str, columns: Optional[List[str]] = None,
                     num_partitions: Optional[int] = None) -> RDD:
        from vega_tpu.io.readers import ParquetColumnReader

        return self.read_source(
            ParquetColumnReader(path, columns,
                                num_partitions or self.default_parallelism)
        )

    # ------------------------------------------------------------ DataFrame
    def read_parquet(self, path: str, columns: Optional[List[str]] = None,
                     num_partitions: Optional[int] = None):
        """Parquet -> DataFrame (vega_tpu/frame): the expression/verb API
        whose planner pushes column pruning and supported predicates into
        ParquetColumnReader and fuses narrow verb chains into one SPMD
        program per stage on the device tier. `columns=` pre-prunes at
        the entry point; the planner prunes further from the query. For
        the raw columnar-block RDD, use parquet_file()."""
        from vega_tpu.frame.api import DataFrame

        return DataFrame.from_parquet(self, path, columns, num_partitions)

    def create_frame(self, columns: Optional[dict] = None,
                     num_partitions: Optional[int] = None, **kwcolumns):
        """In-memory columns -> DataFrame (dict and/or keywords), the
        frame-layer sibling of dense_from_columns."""
        from vega_tpu.frame.api import DataFrame

        data = dict(columns or {})
        for name, c in kwcolumns.items():
            if name in data:
                raise VegaError(f"duplicate column {name!r}")
            data[name] = c
        return DataFrame.from_columns(self, data, num_partitions)

    # Device-tier sources (vega_tpu/tpu): numeric RDDs whose partitions are
    # arrays and whose ops lower to XLA.
    def dense_range(self, n: int, num_partitions: Optional[int] = None,
                    dtype=None, chunk_rows: Optional[int] = None):
        """Device iota source; auto-streams in chunks when block bytes
        times the exchange footprint (~6x) exceed
        Configuration.dense_hbm_budget (see tpu/stream.py)."""
        from vega_tpu.tpu.dense_rdd import dense_range

        return dense_range(self, n, num_partitions or self.default_parallelism,
                           dtype, chunk_rows=chunk_rows)

    def dense_from_numpy(self, *columns, num_partitions: Optional[int] = None):
        from vega_tpu.tpu.dense_rdd import dense_from_numpy

        return dense_from_numpy(
            self, columns, num_partitions or self.default_parallelism
        )

    def dense_from_columns(self, columns: Optional[dict] = None,
                           key: Optional[str] = None, **kwcolumns):
        """Named multi-column dense source (see tpu.dense_rdd.dense_from_columns)."""
        from vega_tpu.tpu.dense_rdd import dense_from_columns

        return dense_from_columns(self, columns, key=key, **kwcolumns)

    def dense_load_npz(self, path: str, chunk_rows: Optional[int] = None):
        """Reload a DenseRDD persisted with save_npz (re-sharded onto the
        current mesh); auto-streams in chunks when block bytes times the
        exchange footprint (~6x) exceed the HBM budget."""
        from vega_tpu.tpu.dense_rdd import dense_load_npz

        return dense_load_npz(self, path, chunk_rows=chunk_rows)

    def dense_hbm_in_use(self) -> int:
        """Tracked device-resident bytes of materialized dense
        intermediates. Intermediates above Configuration.dense_hbm_budget
        are LRU-evicted (lineage recomputes them on next access); sources
        are gated at creation by the streaming planner instead. See the
        lifetime note in tpu/dense_rdd.py."""
        from vega_tpu.tpu.dense_rdd import dense_hbm_in_use

        return dense_hbm_in_use(self)

    def profiler(self, log_dir: str):
        """JAX profiler trace over a block of work (the tracing subsystem
        the reference never built — SURVEY.md §5 'Tracing: none'). View with
        TensorBoard or xprof.

            with ctx.profiler("/tmp/trace"):
                rdd.reduce_by_key(op="add").collect()
        """
        return _profile_trace(log_dir)

    def broadcast(self, value: Any):
        """Driver-side broadcast variable (absent from the reference; Spark
        parity). Local mode shares by reference; distributed mode ships once
        per executor and caches in the BROADCAST key space."""
        from vega_tpu.broadcast import Broadcast

        return Broadcast(self, value)

    # ------------------------------------------------------------------ jobs
    def set_local_property(self, key: str, value) -> None:
        """Thread-local job-submission property (Spark parity). The one
        the scheduler reads is ``"pool"``: jobs submitted from this
        thread land in that fair-scheduling pool. ``None`` clears."""
        props = getattr(self._local_props, "props", None)
        if props is None:
            props = self._local_props.props = {}
        if value is None:
            props.pop(key, None)
        else:
            props[key] = value

    def get_local_property(self, key: str, default=None):
        props = getattr(self._local_props, "props", None)
        return default if props is None else props.get(key, default)

    def set_pool(self, name: str, weight: int = 1,
                 max_concurrent_tasks: Optional[int] = None,
                 max_queued: Optional[int] = None):
        """Declare/configure a scheduling pool (weight skews the fair
        share; max_concurrent_tasks is a hard per-pool in-flight quota;
        max_queued bounds ADMISSION — in-flight jobs of the pool beyond
        it are rejected or blocked per Configuration.admission_mode).
        Select it per thread with ``set_local_property("pool", name)`` or
        per job with ``submit_job(..., pool=name)``."""
        return self.job_server.set_pool(name, weight, max_concurrent_tasks,
                                        max_queued)

    def submit_job(self, rdd: RDD, func: Callable,
                   partitions: Optional[List[int]] = None,
                   pool: Optional[str] = None,
                   transform: Optional[Callable[[list], Any]] = None
                   ) -> JobFuture:
        """Asynchronous job submission: returns a JobFuture immediately;
        the job runs on its own event-loop thread, concurrently with any
        other in-flight jobs, arbitrated by the fair scheduler. `func`
        runs per partition; `transform` (optional) folds the list of
        partition results into the future's final value."""
        self._check_alive()
        if pool is None:
            pool = self.get_local_property("pool")
        return self.job_server.submit(rdd, func, partitions, pool=pool,
                                      transform=transform)

    def run_job(self, rdd: RDD, func: Callable,
                partitions: Optional[List[int]] = None) -> list:
        """Reference: context.rs:457-473. Blocking actions are submit +
        result() on the job server, so pools/quotas/cancellation apply to
        them exactly as to async submissions."""
        self._check_alive()
        if partitions is not None and not partitions:
            return []
        future = self.submit_job(rdd, func, partitions)
        try:
            return future.result()
        except BaseException:
            # The calling thread is unwinding — KeyboardInterrupt in a
            # REPL, most commonly. Pre-PR-7 the event loop ran on THIS
            # thread, so the job died with its caller; preserve that by
            # cancelling the would-be-orphaned job instead of leaving it
            # holding arbiter slots and pool quota to completion. A
            # no-op when the exception IS the job's own error re-raise
            # (the future is already settled; cancel returns False).
            future.cancel("blocking caller interrupted")
            raise

    def run_approximate_job(self, rdd: RDD, func: Callable, evaluator,
                            timeout_s: float) -> PartialResult:
        """Reference: context.rs:510-524 + approximate_action_listener.rs."""
        self._check_alive()
        future = self.job_server.submit(
            rdd, func, list(range(rdd.num_partitions)),
            pool=self.get_local_property("pool"),
            on_task_success=evaluator.merge,
        )
        start = time.time()
        try:
            future.result(timeout_s)
        except TimeoutError:
            # Deadline hit: return the current estimate, deliver the final
            # value when the background job drains (reference:
            # approximate_action_listener.rs:58-111).
            result = PartialResult(evaluator.current_result(), is_final=False)

            def finisher(fut: JobFuture):
                exc = fut.exception()
                if exc is not None:
                    result.set_failure(exc)
                else:
                    result.set_final_value(evaluator.current_result())

            future.add_done_callback(finisher)
            return result
        except BaseException as exc:  # noqa: BLE001 — folded into the result
            result = PartialResult(None, is_final=False)
            result.set_failure(exc)
            return result
        log.debug("approximate job finished in %.3fs", time.time() - start)
        return PartialResult(evaluator.current_result(), is_final=True)

    # ------------------------------------------------------------- streaming
    def streaming(self, batch_interval_s: Optional[float] = None,
                  checkpoint_dir: Optional[str] = None):
        """The Context's micro-batch streaming plane (one per Context,
        created on first use; vega_tpu/streaming/). Interval/checkpoint
        overrides apply only to the creating call."""
        self._check_alive()
        if self._streaming is None:
            from vega_tpu.streaming.context import StreamingContext

            self._streaming = StreamingContext(
                self, batch_interval_s=batch_interval_s,
                checkpoint_dir=checkpoint_dir)
        return self._streaming

    def stream_from_generator(self, fn, **kwargs):
        """DStream over an offset-addressed generator: fn(offset) ->
        record | None. Deterministic + picklable fn = fully replayable
        (the exactly-once reference source)."""
        return self.streaming(**kwargs).generator_stream(fn)

    def stream_from_file_tail(self, path: str, **kwargs):
        """DStream tailing an append-only line file (byte offsets)."""
        return self.streaming(**kwargs).file_tail_stream(path)

    def stream_from_socket(self, host: str, port: int, **kwargs):
        """DStream over line-delimited TCP; every read is bounded by
        stream_socket_timeout_s."""
        return self.streaming(**kwargs).socket_stream(host, port)

    # ----------------------------------------------------------------- admin
    @property
    def default_parallelism(self) -> int:
        return max(2, self._backend.parallelism)

    def metrics_summary(self) -> dict:
        if not self.bus.flush():
            log.warning("event bus flush timed out; metrics may lag")
        return self.metrics.summary()

    def fleet_status(self) -> dict:
        """One view of the serving plane: fleet membership/occupancy
        (per-executor in-flight), the arbiter's running/queued depths
        (global and per pool), per-pool admission in-flight vs bounds,
        and the elastic controller's state. Works in local mode too —
        the fleet section is just empty there."""
        backend = self._backend
        return {
            "fleet": backend.fleet_snapshot()
            if hasattr(backend, "fleet_snapshot") else [],
            "scheduler": self.job_server.arbiter.stats(),
            "admission": self.job_server.admission_status(),
            "elastic": self.elastic.status() if self.elastic is not None
            else {"enabled": False},
            "pool_latency": self.metrics.pool_latency(),
            "streaming": self._streaming.status()
            if self._streaming is not None else {"active": False},
        }

    def storage_status(self) -> dict:
        """Tier occupancy + spill/promote counters of this process's block
        stores (cache + shuffle). bench.py embeds this in its detail so
        HBM/RSS numbers can attribute spill cost."""
        env = Env.get()
        return {
            "cache": env.cache.status(),
            "shuffle": env.shuffle_store.status(),
        }

    def stop(self) -> None:
        """Reference: context.rs:131-144 (drop/cleanup)."""
        assert_role()  # driver teardown — never from a confined thread
        global _active_context
        if self._stopped:
            return
        self._stopped = True
        # Streaming stops FIRST: its batch loop submits jobs and its
        # receivers write the cache — both must quiesce before the job
        # plane and stores they ride on wind down.
        if self._streaming is not None:
            try:
                self._streaming.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                log.warning("streaming stop failed", exc_info=True)
        # The autoscaler goes first: a control loop mid-decision must not
        # spawn or decommission against a backend that is tearing down
        # (teardown=True also aborts any mid-ladder decommission).
        if self.elastic is not None:
            self.elastic.stop(teardown=True)
        # Wind the job plane down first: cancel in-flight jobs and settle
        # their futures (nobody stays parked on result()) BEFORE the
        # backend and stores those jobs might still be touching go away.
        self.job_server.stop()
        self.scheduler.stop()
        env = Env.get()
        env.shuffle_store.close()  # clears both tiers + removes spill dir
        env.cache.close()
        from vega_tpu.env import detach_session_logger

        detach_session_logger(self._log_handler, self.conf.log_cleanup)
        self._log_handler = None
        log.setLevel(self._prev_logger_level)
        with _active_context_lock:
            if _active_context is self:
                _active_context = None

    def _check_alive(self):
        if self._stopped:
            raise RuntimeError("Context is stopped")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- pickling
    # RDD lineages hold a Context reference; tasks serialize lineages. The
    # Context itself must not travel (it owns threads and sockets) — ship a
    # handle that rebinds to the process-active context, mirroring the
    # reference's weak Context ref inside RddVals (rdd/rdd.rs:54-76).
    def __reduce__(self):
        return (_deserialize_context, ())


class _StubContext:
    """Context stand-in inside executor processes: id counters only."""

    def __init__(self):
        self._next_rdd_id = itertools.count(1 << 40)
        self._next_shuffle_id = itertools.count(1 << 40)

    def new_rdd_id(self):
        return next(self._next_rdd_id)

    def new_shuffle_id(self):
        return next(self._next_shuffle_id)

    def run_job(self, *_a, **_k):
        raise RuntimeError("run_job is driver-only; executors compute partitions")

    def __reduce__(self):
        return (_deserialize_context, ())


_stub_context: Optional[_StubContext] = None


def _deserialize_context():
    global _stub_context
    with _active_context_lock:
        if _active_context is not None:
            return _active_context
    if _stub_context is None:
        _stub_context = _StubContext()
    return _stub_context
