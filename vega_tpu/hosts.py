"""Cluster membership file (reference: src/hosts.rs).

The reference reads ~/hosts.conf (TOML: master + slave list,
config_files/hosts.conf) to drive its scp/ssh bootstrap. vega_tpu reads an
INI-simple file (no TOML dependency) with the same content model:

    master = 10.0.0.1
    slaves = 10.0.0.2, 10.0.0.3:2, 10.0.0.4

A slave entry `host:N` launches N executor workers on that host. Lines
starting with '#' are comments. Used by Context("distributed",
hosts_file=...) / VEGA_TPU_HOSTS_FILE; absent file means local executors.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

from vega_tpu.errors import VegaError

DEFAULT_PATH = os.path.expanduser("~/hosts.conf")


@dataclass
class Hosts:
    master: str = "127.0.0.1"
    slaves: List[str] = field(default_factory=list)  # expanded host list

    @staticmethod
    def parse(text: str) -> "Hosts":
        master = "127.0.0.1"
        slaves: List[str] = []
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            if "=" not in line:
                raise VegaError(f"hosts file line {lineno}: expected key = value")
            key, _, value = line.partition("=")
            key = key.strip().lower()
            value = value.strip()
            if key == "master":
                master = value
            elif key == "slaves":
                for entry in value.split(","):
                    entry = entry.strip()
                    if not entry:
                        continue
                    host, _, count = entry.partition(":")
                    n = 1
                    if count:
                        try:
                            n = int(count)
                        except ValueError as e:
                            raise VegaError(
                                f"hosts file line {lineno}: bad count {count!r}"
                            ) from e
                        if n < 0:
                            raise VegaError(
                                f"hosts file line {lineno}: negative count {n}"
                            )
                    slaves.extend([host] * n)  # host:0 drains the host
            else:
                raise VegaError(f"hosts file line {lineno}: unknown key {key!r}")
        return Hosts(master=master, slaves=slaves)

    @staticmethod
    def load(path: Optional[str] = None) -> "Hosts":
        """Reference: hosts.rs:19-38 (Hosts::get)."""
        path = path or os.environ.get("VEGA_TPU_HOSTS_FILE") or DEFAULT_PATH
        if not os.path.exists(path):
            return Hosts()
        with open(path) as f:
            return Hosts.parse(f.read())
