"""Runtime lock-order sanitizer: the dynamic half of VG003.

Static lock-order analysis (vega_tpu/lint/rules.py VG003) sees lexical
nesting and one resolvable call hop; it cannot see orders that only arise
through callbacks, scheduler interleavings, or data-dependent paths. Under
``VEGA_TPU_DEBUG_SYNC=1`` the project's named locks are wrapped so every
acquisition is recorded into a global order graph per thread:

- acquiring B while holding A adds the edge A -> B (first-site attributed);
- acquiring B while a path B -> ... -> A already exists for some held A is
  an ORDER INVERSION: two threads running both orders concurrently can
  deadlock. The witness raises :class:`LockOrderError` at the acquisition
  site (the earliest, most debuggable moment) AND records the inversion, so
  even if a broad handler swallows the raise, ``check_clean()`` — wired
  into tests/conftest.py at session finish — still fails the run;
- re-acquiring a non-reentrant witnessed lock on the same thread is an
  immediate self-deadlock report instead of a silent hang.

With the flag unset (the default, and every production path)
:func:`named_lock` returns a plain ``threading.Lock``/``RLock`` — zero
overhead, zero behavior change. The wrapper intentionally does NOT support
``threading.Condition`` (Condition pokes lock internals); condition locks
(map_output_tracker) stay plain.

Role witnesses (vegalint v3) ride the same flag: the long-lived threads
call :func:`note_thread_role` at their entry point, which cross-checks
the OBSERVED thread identity against the declared role map
(vega_tpu/lint/callgraph.ROLES — the same table the static VG016/VG019
rules propagate from), and driver-only functions call
:func:`assert_role` so a confined thread (worker task handler, streaming
receiver) reaching one fails the run with the offending call path. Both
record into the witness even when the raise is swallowed, so
``check_clean()`` still fails the session. With the flag unset every
role function is a no-op.

This module must import nothing beyond the stdlib: core modules construct
locks at import time, long before jax or the rest of vega_tpu is safe to
touch. (callgraph is imported lazily, only under the debug flag — it is
stdlib-pure too, but keeping the import-time surface minimal is the
contract.)
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple


class LockOrderError(AssertionError):
    """Two locks acquired in opposite orders (or a self-deadlock)."""


class RoleError(AssertionError):
    """Observed thread identity disagrees with the declared role map, or
    a confined thread reached a driver-only function."""


def enabled() -> bool:
    return os.environ.get("VEGA_TPU_DEBUG_SYNC") == "1"


class _Witness:
    """Global acquisition-order graph. One per process."""

    def __init__(self) -> None:
        self._mu = threading.Lock()  # guards the graph, never held while
        # blocking on a witnessed lock (check / record bracket the inner
        # acquire, they do not span it)
        # edge a -> b: b acquired while a held; value = first observed site
        self._edges: Dict[Tuple[str, str], str] = {}
        self._tls = threading.local()
        self.inversions: List[str] = []
        # role -> thread names observed carrying it (role witnesses)
        self.roles_observed: Dict[str, Set[str]] = {}
        self.role_violations: List[str] = []

    # ------------------------------------------------------------ per thread
    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # ---------------------------------------------------------------- graph
    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        if src == dst:
            return [src]
        parent = {src: src}
        frontier = [src]
        while frontier:
            nxt = []
            for u in frontier:
                for (a, b) in self._edges:
                    if a != u or b in parent:
                        continue
                    parent[b] = u
                    if b == dst:
                        path = [b]
                        while path[-1] != src:
                            path.append(parent[path[-1]])
                        return list(reversed(path))
                    nxt.append(b)
            frontier = nxt
        return None

    def _site(self, depth: int = 3) -> str:
        try:
            f = sys._getframe(depth)
            return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
        except (ValueError, AttributeError):
            return "?"

    # ------------------------------------------------------------- protocol
    def before_acquire(self, name: str, reentrant: bool) -> None:
        held = self._held()
        if name in held:
            if reentrant:
                return
            msg = (f"self-deadlock: non-reentrant lock '{name}' "
                   f"re-acquired on {threading.current_thread().name} "
                   f"at {self._site()} while already held")
            with self._mu:
                self.inversions.append(msg)
            raise LockOrderError(msg)
        with self._mu:
            for h in held:
                path = self._path(name, h)
                if path is None:
                    continue
                first = self._edges.get((path[0], path[1]), "?") \
                    if len(path) > 1 else "?"
                msg = (
                    f"lock-order inversion: acquiring '{name}' while "
                    f"holding '{h}' on "
                    f"{threading.current_thread().name} at "
                    f"{self._site()}, but the reverse order "
                    f"{' -> '.join(path)} was already observed "
                    f"(first at {first}); concurrent threads running "
                    "both orders deadlock")
                self.inversions.append(msg)
                raise LockOrderError(msg)

    def after_acquire(self, name: str, reentrant: bool) -> None:
        held = self._held()
        if reentrant and name in held:
            return  # recursive level, no new edges
        site = self._site()
        with self._mu:
            for h in held:
                self._edges.setdefault((h, name), site)
        held.append(name)

    def on_release(self, name: str) -> None:
        held = self._held()
        # Out-of-stack-order release is legal (Python locks allow it);
        # drop the most recent occurrence.
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # --------------------------------------------------------------- roles
    def _call_path(self, skip: int = 2) -> str:
        """Compact caller chain for violation messages (innermost last)."""
        frames = traceback.extract_stack()[:-skip]
        tail = [f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}"
                for f in frames[-6:]]
        return " -> ".join(tail)

    def note_role(self, role: str) -> None:
        from vega_tpu.lint import callgraph  # lazy: debug-flag-only path

        spec = callgraph.ROLES.get(role)
        tname = threading.current_thread().name
        if spec is None:
            msg = (f"role witness: '{role}' noted on thread '{tname}' is "
                   f"not in the declared role map "
                   f"(callgraph.ROLES) — add it there first; at "
                   f"{self._call_path()}")
            with self._mu:
                self.role_violations.append(msg)
            raise RoleError(msg)
        prefixes = spec["thread_prefixes"]
        if prefixes and not any(tname.startswith(p) for p in prefixes):
            msg = (f"role witness: thread '{tname}' noted role '{role}' "
                   f"but the declared map expects a name starting with "
                   f"{prefixes} — the static role map and the runtime "
                   f"disagree; fix whichever is wrong; at "
                   f"{self._call_path()}")
            with self._mu:
                self.role_violations.append(msg)
            raise RoleError(msg)
        self._tls.role = role
        with self._mu:
            self.roles_observed.setdefault(role, set()).add(tname)

    def current_role(self) -> Optional[str]:
        return getattr(self._tls, "role", None)

    def check_role(self, allowed: Tuple[str, ...]) -> None:
        from vega_tpu.lint import callgraph  # lazy: debug-flag-only path

        role = self.current_role()
        if role is None or role not in callgraph.CONFINED_ROLES \
                or role in allowed:
            return  # un-noted threads and unconfined roles always pass
        msg = (f"role confinement violated: driver-only function reached "
               f"from confined role '{role}' on thread "
               f"'{threading.current_thread().name}' via "
               f"{self._call_path(skip=3)}")
        with self._mu:
            self.role_violations.append(msg)
        raise RoleError(msg)

    # ------------------------------------------------------------ reporting
    def stats(self) -> dict:
        with self._mu:
            return {
                "locks": len({n for e in self._edges for n in e}),
                "edges": len(self._edges),
                "inversions": list(self.inversions),
                "roles": {r: sorted(t)
                          for r, t in self.roles_observed.items()},
                "role_violations": list(self.role_violations),
            }


_WITNESS = _Witness()


def witness() -> _Witness:
    return _WITNESS


def check_clean() -> None:
    """Raise if any inversion OR role violation was recorded this process
    — even one whose in-place error was swallowed by a broad handler
    (exactly the blindness VG005 exists for). Wired into conftest at
    session finish."""
    st = witness().stats()
    inv = st["inversions"]
    if inv:
        raise LockOrderError(
            f"{len(inv)} lock-order inversion(s) recorded:\n"
            + "\n".join(inv))
    rv = st["role_violations"]
    if rv:
        raise RoleError(
            f"{len(rv)} role violation(s) recorded:\n" + "\n".join(rv))


def note_thread_role(role: str) -> None:
    """Record the calling thread's declared role (no-op unless
    VEGA_TPU_DEBUG_SYNC=1). Placed at the entry point of each long-lived
    role thread; cross-checks the observed thread name against
    callgraph.ROLES and fails the run on disagreement."""
    if enabled():
        _WITNESS.note_role(role)


def current_role() -> Optional[str]:
    return _WITNESS.current_role() if enabled() else None


def assert_role(*allowed: str) -> None:
    """Guard for driver-only functions (no-op unless
    VEGA_TPU_DEBUG_SYNC=1): raises RoleError when called from a thread
    noted with a CONFINED role (worker task handler, streaming receiver)
    not in `allowed`. Un-noted threads — the driver main thread, test
    threads — always pass; this is the runtime mirror of VG019, not a
    general ACL."""
    if enabled():
        _WITNESS.check_role(allowed)


class WitnessLock:
    """threading.Lock with acquisition-order witnessing. API-compatible
    for `with`, acquire(blocking, timeout), release(), locked()."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = self._make_inner()

    def _make_inner(self):
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _WITNESS.before_acquire(self.name, self._reentrant)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _WITNESS.after_acquire(self.name, self._reentrant)
        return got

    def release(self) -> None:
        # Order matters: pop the witness record only after the inner
        # release cannot fail (releasing an unheld lock raises).
        self._inner.release()
        _WITNESS.on_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class WitnessRLock(WitnessLock):
    """Reentrant variant: recursive re-acquisition is legal and adds no
    edges; the witness entry pops on the outermost release only."""

    _reentrant = True

    def __init__(self, name: str):
        super().__init__(name)
        self._depth_tls = threading.local()

    def _make_inner(self):
        return threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _WITNESS.before_acquire(self.name, True)
        got = self._inner.acquire(blocking, timeout)
        if got:
            depth = getattr(self._depth_tls, "d", 0)
            if depth == 0:
                _WITNESS.after_acquire(self.name, True)
            self._depth_tls.d = depth + 1
        return got

    def release(self) -> None:
        self._inner.release()
        depth = getattr(self._depth_tls, "d", 1) - 1
        self._depth_tls.d = depth
        if depth == 0:
            _WITNESS.on_release(self.name)


def named_lock(name: str, reentrant: bool = False, force: bool = False):
    """The project's lock constructor. Returns a plain threading lock
    unless VEGA_TPU_DEBUG_SYNC=1 (or force=True, for the witness's own
    tests), in which case the acquisition order of every named lock is
    recorded per thread and inversions raise LockOrderError."""
    if force or enabled():
        return WitnessRLock(name) if reentrant else WitnessLock(name)
    return threading.RLock() if reentrant else threading.Lock()
