"""CLI: python -m vega_tpu.lint [paths...] [--output text|json]
[--json-out PATH] [--select VG001,VG003] [--list-rules] [--no-cache]

Exit status: 0 clean, 1 unsuppressed findings (or unparseable files),
2 usage error. The tier-1 entrypoint (scripts/t1.sh) gates on this via
scripts/lint.sh, which also writes the machine-readable finding JSON
(stable schema: engine.JSON_SCHEMA) to /tmp/vegalint.json via
--json-out for CI artifact pickup.
"""

from __future__ import annotations

import argparse
import sys

from vega_tpu.lint.engine import (
    all_rules,
    render_json,
    render_text,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vega_tpu.lint",
        description="vegalint: machine-checked vega_tpu invariants "
                    "(catalog: docs/LINTING.md)")
    parser.add_argument("paths", nargs="*",
                        default=["vega_tpu", "tests", "bench.py"],
                        help="files or directories (default: the tier-1 "
                             "sweep set)")
    parser.add_argument("--format", "--output", dest="format",
                        choices=("text", "json"), default="text",
                        help="stdout format (--output is an alias)")
    parser.add_argument("--json-out", default=None, metavar="PATH",
                        help="additionally write the JSON report (stable "
                             "finding schema) to PATH — CI artifact")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run (default: "
                             "all)")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the mtime-keyed result cache")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted(all_rules().items()):
            print(f"{rid}  {r.title}")
            doc = " ".join((r.doc or "").split())
            if doc:
                print(f"       {doc}")
        return 0

    select = [s.strip() for s in args.select.split(",")] \
        if args.select else None
    try:
        result = run_lint(args.paths, select=select,
                          cache=not args.no_cache)
    except ValueError as exc:  # unknown --select rule id
        print(f"vegalint: {exc}", file=sys.stderr)
        return 2
    if args.json_out:
        try:
            with open(args.json_out, "w", encoding="utf-8") as f:
                f.write(render_json(result) + "\n")
        except OSError as exc:
            # The artifact is a convenience copy; an IO failure (foreign
            # file in a shared temp dir, read-only fs) must not make a
            # clean tree look like a failed gate.
            print(f"vegalint: could not write --json-out artifact: {exc}",
                  file=sys.stderr)
    print(render_json(result) if args.format == "json"
          else render_text(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
