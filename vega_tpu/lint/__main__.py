"""CLI: python -m vega_tpu.lint [paths...] [--format text|json]
[--select VG001,VG003] [--list-rules]

Exit status: 0 clean, 1 unsuppressed findings (or unparseable files),
2 usage error. The tier-1 entrypoint (scripts/t1.sh) gates on this via
scripts/lint.sh.
"""

from __future__ import annotations

import argparse
import sys

from vega_tpu.lint.engine import (
    all_rules,
    render_json,
    render_text,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vega_tpu.lint",
        description="vegalint: machine-checked vega_tpu invariants "
                    "(catalog: docs/LINTING.md)")
    parser.add_argument("paths", nargs="*",
                        default=["vega_tpu", "tests", "bench.py"],
                        help="files or directories (default: the tier-1 "
                             "sweep set)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run (default: "
                             "all)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted(all_rules().items()):
            print(f"{rid}  {r.title}")
            doc = " ".join((r.doc or "").split())
            if doc:
                print(f"       {doc}")
        return 0

    select = [s.strip() for s in args.select.split(",")] \
        if args.select else None
    try:
        result = run_lint(args.paths, select=select)
    except ValueError as exc:  # unknown --select rule id
        print(f"vegalint: {exc}", file=sys.stderr)
        return 2
    print(render_json(result) if args.format == "json"
          else render_text(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
