"""CLI: python -m vega_tpu.lint [paths...] [--output text|json]
[--json-out PATH] [--select VG001,VG003] [--list-rules] [--no-cache]
[--changed] [--explain-role module.fn]

Exit status: 0 clean, 1 unsuppressed findings (or unparseable files),
2 usage error. The tier-1 entrypoint (scripts/t1.sh) gates on this via
scripts/lint.sh, which also writes the machine-readable finding JSON
(stable schema: engine.JSON_SCHEMA) to /tmp/vegalint.json via
--json-out for CI artifact pickup.

--changed lints only files modified since the last CLEAN full sweep
(the stamp rides next to the result cache): nothing changed is an
instant pass; a change under vega_tpu/ falls back to the full sweep
(the project call graph's inputs changed); otherwise only the per-file
rules run on the changed files (project rules and the VG000
orphan-pragma check need full-tree context, so pre-commit speed trades
them away — scripts/t1.sh keeps the full sweep).

--explain-role prints the thread role(s) a function resolves to in the
project call graph plus one witness call path per role — the debugging
lens for VG016/VG019 findings.
"""

from __future__ import annotations

import argparse
import json
import sys

from vega_tpu.lint.engine import (
    JSON_SCHEMA,
    all_rules,
    changed_since_stamp,
    gather_extracts,
    render_json,
    render_text,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vega_tpu.lint",
        description="vegalint: machine-checked vega_tpu invariants "
                    "(catalog: docs/LINTING.md)")
    parser.add_argument("paths", nargs="*",
                        default=["vega_tpu", "tests", "bench.py"],
                        help="files or directories (default: the tier-1 "
                             "sweep set)")
    parser.add_argument("--format", "--output", dest="format",
                        choices=("text", "json"), default="text",
                        help="stdout format (--output is an alias)")
    parser.add_argument("--json-out", default=None, metavar="PATH",
                        help="additionally write the JSON report (stable "
                             "finding schema) to PATH — CI artifact")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run (default: "
                             "all)")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the mtime-keyed result cache")
    parser.add_argument("--changed", action="store_true",
                        help="lint only files changed since the last "
                             "clean full sweep (falls back to full when "
                             "vega_tpu/ itself changed or no stamp "
                             "exists)")
    parser.add_argument("--explain-role", default=None, metavar="FN",
                        help="print the role(s) a function (module.fn "
                             "or Class.method suffix) resolves to, with "
                             "one witness call path per role")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted(all_rules().items()):
            print(f"{rid}  {r.title}")
            doc = " ".join((r.doc or "").split())
            if doc:
                print(f"       {doc}")
        return 0

    if args.explain_role:
        from vega_tpu.lint import callgraph

        records = gather_extracts(args.paths, "callgraph",
                                  cache=not args.no_cache)
        matches = callgraph.explain(records, args.explain_role)
        if args.format == "json":
            print(json.dumps({"schema": JSON_SCHEMA,
                              "query": args.explain_role,
                              "matches": matches},
                             indent=1, sort_keys=True))
        else:
            for m in matches:
                print(f"{m['function']}  ({m['file']}:{m['line']})")
                if not m["roles"]:
                    print("    roles: none (driver-api by default)")
                for role, path in m["roles"].items():
                    print(f"    {role}: {' -> '.join(path)}")
            if not matches:
                print(f"no function matching {args.explain_role!r} in "
                      "the call graph", file=sys.stderr)
        return 0 if matches else 2

    select = [s.strip() for s in args.select.split(",")] \
        if args.select else None
    if args.changed and select is None:
        changed = changed_since_stamp(args.paths)
        if changed is not None:
            if any("/vega_tpu/" in "/" + p.replace("\\", "/").lstrip("./")
                   for p in changed):
                pass  # graph inputs changed: keep the full sweep
            else:
                # Narrow run: per-file rules on just the changed files.
                # A clean narrow run does NOT move the stamp (only a
                # full sweep proves the tree clean).
                args.paths = changed
                select = [rid for rid, r in all_rules().items()
                          if not r.project]
    try:
        result = run_lint(args.paths, select=select,
                          cache=not args.no_cache)
    except ValueError as exc:  # unknown --select rule id
        print(f"vegalint: {exc}", file=sys.stderr)
        return 2
    if args.json_out:
        try:
            with open(args.json_out, "w", encoding="utf-8") as f:
                f.write(render_json(result) + "\n")
        except OSError as exc:
            # The artifact is a convenience copy; an IO failure (foreign
            # file in a shared temp dir, read-only fs) must not make a
            # clean tree look like a failed gate.
            print(f"vegalint: could not write --json-out artifact: {exc}",
                  file=sys.stderr)
    print(render_json(result) if args.format == "json"
          else render_text(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
