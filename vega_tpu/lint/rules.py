"""vegalint rules VG001–VG008: the project invariants as AST checks.

Each rule encodes one CLAUDE.md invariant (see docs/LINTING.md for the
catalog with rationale and examples). Rules are deliberately conservative:
a rule that cries wolf gets pragma'd into silence, and then the invariant
is unguarded again — so every heuristic here is tuned to the failure mode
that actually bit this repo, not to theoretical completeness. The dynamic
complement (vega_tpu/lint/sync_witness.py) covers what lexical analysis
cannot see at runtime.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from vega_tpu.lint.engine import FileCtx, Finding, rule

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _last_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _base_name(node: ast.AST) -> Optional[str]:
    """Leftmost identifier of an attribute chain (`a.b.c()` -> 'a')."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


def _own_nodes(root: ast.AST) -> Iterator[ast.AST]:
    """Descendants of `root` excluding nested function/lambda subtrees —
    the code that actually runs when `root`'s body runs."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# VG001 — raw jax spellings that must go through tpu/compat.py
# ---------------------------------------------------------------------------
# jax.shard_map / jax.enable_x64 / jax.export do not exist on jax < 0.5 and
# lax.platform_dependent lowers every branch there; writing any of them
# directly wiped out the entire dense tier at seed (fixed in PR 1 by
# vega_tpu/tpu/compat.py). Only compat.py may touch the raw surface.

_VG001_BANNED = (
    "jax.shard_map",
    "jax.enable_x64",
    "jax.export",
    "jax.lax.platform_dependent",
    "jax.experimental.shard_map",
    "jax.experimental.enable_x64",
)


def _banned_prefix(qual: Optional[str]) -> Optional[str]:
    if qual is None:
        return None
    for b in _VG001_BANNED:
        if qual == b or qual.startswith(b + "."):
            return b
    return None


@rule("VG001", "raw jax compat-surface spelling outside tpu/compat.py")
def vg001(ctx: FileCtx) -> Iterator[Finding]:
    if ctx.endswith("tpu/compat.py"):
        return
    # Import sites: `from jax.experimental.shard_map import ...`,
    # `from jax import export`, `from jax.lax import platform_dependent`.
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.level == 0 \
                and node.module:
            for a in node.names:
                b = _banned_prefix(f"{node.module}.{a.name}")
                if b:
                    yield Finding(
                        "VG001", ctx.display, node.lineno,
                        node.col_offset + 1,
                        f"import of {node.module}.{a.name}: use "
                        "vega_tpu.tpu.compat (jax<0.5 has a different "
                        "surface — this exact drift wiped the dense tier "
                        "at seed)")
    # Use sites: outermost Name/Attribute chains whose alias-expanded
    # dotted name lands on the banned surface.
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Attribute, ast.Name)):
            continue
        if isinstance(parents.get(node), ast.Attribute):
            continue  # inner link of a longer chain; outermost reports
        if isinstance(node, ast.Name) and not isinstance(
                node.ctx, ast.Load):
            continue
        qual = ctx.qualified(node)
        b = _banned_prefix(qual)
        if b:
            yield Finding(
                "VG001", ctx.display, node.lineno, node.col_offset + 1,
                f"raw '{qual}' — use the vega_tpu.tpu.compat shim "
                "(CLAUDE.md: ALL dense-tier code goes through compat.py)")


# ---------------------------------------------------------------------------
# VG002 — device probes reachable at module import time
# ---------------------------------------------------------------------------
# jax.devices()/default_backend() initialize the backend; on a wedged axon
# tunnel that call hangs forever, so CLAUDE.md bans it from import paths
# (and conftest's forced CPU mesh must run before any backend init).

_VG002_PROBES = {
    "jax.devices",
    "jax.default_backend",
    "jax.local_devices",
    "jax.device_count",
}


def _is_main_guard(test: ast.AST) -> bool:
    return (isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == "__name__"
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value == "__main__")


@rule("VG002", "device probe reachable at module import time")
def vg002(ctx: FileCtx) -> Iterator[Finding]:
    # Local functions that probe: a module-level call to one of them is
    # just as import-hanging as the probe itself (one hop, same module).
    probe_funcs: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_DEFS):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and ctx.qualified(sub.func) in _VG002_PROBES:
                    probe_funcs.add(node.name)
                    break

    findings: List[Finding] = []

    def walk(node: ast.AST, import_time: bool) -> None:
        if isinstance(node, _FUNC_DEFS):
            # Decorators and argument defaults DO run at import time;
            # the body does not.
            for d in node.decorator_list:
                walk(d, import_time)
            for d in list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None]:
                walk(d, import_time)
            for b in node.body:
                walk(b, False)
            return
        if isinstance(node, ast.Lambda):
            walk(node.body, False)
            return
        if isinstance(node, ast.If) and _is_main_guard(node.test):
            # `if __name__ == "__main__":` runs as a script entry, not on
            # import — but its ELSE branch is exactly what runs on import.
            for b in node.body:
                walk(b, False)
            for b in node.orelse:
                walk(b, import_time)
            return
        if import_time and isinstance(node, ast.Call):
            qual = ctx.qualified(node.func)
            if qual in _VG002_PROBES:
                findings.append(Finding(
                    "VG002", ctx.display, node.lineno, node.col_offset + 1,
                    f"'{qual}()' runs at module import time — backend "
                    "init on an import path hangs forever on a wedged "
                    "device tunnel (CLAUDE.md environment quirk)"))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in probe_funcs:
                findings.append(Finding(
                    "VG002", ctx.display, node.lineno, node.col_offset + 1,
                    f"module-level call to '{node.func.id}()', which "
                    "probes jax devices — backend init on an import path "
                    "hangs on a wedged tunnel"))
        for child in ast.iter_child_nodes(node):
            walk(child, import_time)

    walk(ctx.tree, True)
    yield from findings


# ---------------------------------------------------------------------------
# VG003 — lock-order graph: cycles + blocking calls under cache/store locks
# ---------------------------------------------------------------------------
# The seed suite froze on exactly this: two task threads interleaving
# device slicing + device_get deadlocked old XLA:CPU on the 1-core box.
# The rule builds the acquisition graph over threading.Lock/RLock (and
# sync_witness.named_lock) attributes across vega_tpu/, flags cycles, and
# flags blocking calls (device_get/host_get, socket recv, Future.result,
# queue.get without timeout) made while holding _host_cache_lock or any
# cache/store lock. Lexical nesting plus one resolvable call hop; the
# runtime sync_witness covers dynamic orders statically invisible here.

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_RLOCK_CTORS = {"threading.RLock"}


def _lock_ctor(call: ast.AST, ctx: FileCtx) -> Optional[bool]:
    """None if not a lock constructor; else True when reentrant."""
    if not isinstance(call, ast.Call):
        return None
    qual = ctx.qualified(call.func)
    if qual in _LOCK_CTORS:
        return qual in _RLOCK_CTORS
    if _last_name(call.func) == "named_lock":
        for k in call.keywords:
            if k.arg == "reentrant" and isinstance(k.value, ast.Constant):
                return bool(k.value.value)
        return False
    return None


class _Vg003State:
    def __init__(self) -> None:
        self.locks: Dict[str, bool] = {}  # key -> reentrant
        # (a, b) -> (display, line) of first `acquire b while holding a`
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        # (module, cls, fname) -> direct lock keys it acquires
        self.fn_locks: Dict[Tuple[str, Optional[str], str], Set[str]] = {}
        # deferred call hops: (held keys, callee, display, line)
        self.calls: List[Tuple[List[str], Tuple, str, int]] = []
        self.findings: List[Finding] = []


def _vg003_lock_key(expr: ast.AST, ctx: FileCtx, cls: Optional[str],
                    state: _Vg003State) -> Optional[str]:
    if isinstance(expr, ast.Name):
        key = f"{ctx.module}.{expr.id}"
        if key in state.locks:
            return key
        alias = ctx.aliases.get(expr.id)
        if alias and alias in state.locks:
            return alias
        return key if "lock" in expr.id.lower() else None
    if isinstance(expr, ast.Attribute):
        base = _base_name(expr)
        if base == "self" and isinstance(expr.value, ast.Name):
            key = f"{ctx.module}.{cls}.{expr.attr}" if cls else None
            if key:
                return key if (key in state.locks
                               or "lock" in expr.attr.lower()) else None
        qual = ctx.qualified(expr)
        if qual and qual in state.locks:
            return qual
        if "lock" in expr.attr.lower():
            return f"{ctx.module}.?.{expr.attr}"  # opaque foreign lock
    return None


_CACHEISH = ("cache", "store")


def _is_cacheish(key: str) -> bool:
    low = key.lower()
    return any(s in low for s in _CACHEISH)


def _blocking_desc(call: ast.Call) -> Optional[str]:
    name = _last_name(call.func)
    if name in ("device_get", "host_get"):
        return f"{name}() (a driver<->device round trip)"
    if name == "recv":
        return "socket recv()"
    if name == "result" and not call.args and not _kw(call, "timeout"):
        return "Future.result() without timeout"
    if name == "get" and isinstance(call.func, ast.Attribute) \
            and not call.args and not _kw(call, "timeout"):
        recv = _base_name(call.func) or ""
        attr_chain = call.func.value
        attr = attr_chain.attr if isinstance(attr_chain, ast.Attribute) \
            else recv
        if "queue" in (attr or "").lower() or "queue" in recv.lower():
            return "queue get() without timeout"
    return None


def _vg003_scan_fn(body: List[ast.stmt], ctx: FileCtx, cls: Optional[str],
                   fname: str, state: _Vg003State) -> None:
    direct: Set[str] = set()
    nested: List[Tuple[List[ast.stmt], Optional[str], str]] = []

    def walk(node: ast.AST, held: List[str]) -> None:
        if isinstance(node, _FUNC_DEFS):
            nested.append((node.body, cls, node.name))
            return  # a nested def runs later, not under the held locks
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.With):
            here: List[str] = []
            for item in node.items:
                walk(item.context_expr, held + here)
                key = _vg003_lock_key(item.context_expr, ctx, cls, state)
                if key is None:
                    continue
                for h in held + here:
                    if h == key and state.locks.get(key):
                        continue  # reentrant re-acquire is fine
                    state.edges.setdefault(
                        (h, key), (ctx.display, item.context_expr.lineno))
                here.append(key)
                direct.add(key)
            for b in node.body:
                walk(b, held + here)
            return
        if isinstance(node, ast.Call):
            desc = _blocking_desc(node)
            cacheish = [h for h in held if _is_cacheish(h)]
            if desc and cacheish:
                state.findings.append(Finding(
                    "VG003", ctx.display, node.lineno,
                    node.col_offset + 1,
                    f"blocking {desc} while holding cache/store lock "
                    f"'{cacheish[-1]}' — can deadlock or starve the "
                    "1-core sandbox (the seed-suite XLA:CPU wedge)"))
            if held:
                callee: Optional[Tuple] = None
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self" and cls:
                    callee = (ctx.module, cls, f.attr)
                elif isinstance(f, ast.Name):
                    callee = (ctx.module, None, f.id)
                if callee is not None:
                    state.calls.append(
                        (list(held), callee, ctx.display, node.lineno))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in body:
        walk(stmt, [])
    fn_key = (ctx.module, cls, fname)
    state.fn_locks.setdefault(fn_key, set()).update(direct)
    for nbody, ncls, nname in nested:
        _vg003_scan_fn(nbody, ctx, ncls, nname, state)


@rule("VG003", "lock-order cycles and blocking calls under cache/store "
      "locks", project=True)
def vg003(ctxs: List[FileCtx]) -> Iterator[Finding]:
    ctxs = [c for c in ctxs if c.in_dir("vega_tpu")]
    state = _Vg003State()
    # Pass 1: lock definitions (module-level names and self.X attributes).
    for ctx in ctxs:
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Name):
                r = _lock_ctor(node.value, ctx)
                if r is not None:
                    state.locks[f"{ctx.module}.{node.targets[0].id}"] = r
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                r = _lock_ctor(sub.value, ctx)
                if r is None:
                    continue
                t = sub.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    state.locks[f"{ctx.module}.{node.name}.{t.attr}"] = r
                elif isinstance(t, ast.Name):  # class-body lock (Env._lock)
                    state.locks[f"{ctx.module}.{node.name}.{t.id}"] = r
    # Pass 2: acquisitions — module body, functions, methods.
    for ctx in ctxs:
        _vg003_scan_fn(
            [s for s in ctx.tree.body
             if not isinstance(s, _FUNC_DEFS + (ast.ClassDef,))],
            ctx, None, "<module>", state)
        for node in ctx.tree.body:
            if isinstance(node, _FUNC_DEFS):
                _vg003_scan_fn(node.body, ctx, None, node.name, state)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, _FUNC_DEFS):
                        _vg003_scan_fn(sub.body, ctx, node.name,
                                       sub.name, state)
    # Pass 3: one call hop — held locks flow into the callee's direct set.
    for held, callee, display, line in state.calls:
        for key in state.fn_locks.get(callee, ()):
            for h in held:
                if h == key and state.locks.get(key):
                    continue
                state.edges.setdefault((h, key), (display, line))
    # Pass 4: cycles (including non-reentrant self-acquisition).
    adj: Dict[str, Set[str]] = {}
    for (a, b), _site in state.edges.items():
        adj.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()
    for (a, b), (display, line) in sorted(state.edges.items(),
                                          key=lambda kv: kv[1]):
        if a == b:
            state.findings.append(Finding(
                "VG003", display, line, 1,
                f"non-reentrant lock '{a}' re-acquired while already "
                "held — self-deadlock"))
            continue
        path = _find_path(adj, b, a)
        if path is None:
            continue
        cycle = [a] + path[:-1]  # path ends at a; drop the repeat
        lo = cycle.index(min(cycle))
        canon = tuple(cycle[lo:] + cycle[:lo])
        if canon in seen_cycles:
            continue
        seen_cycles.add(canon)
        state.findings.append(Finding(
            "VG003", display, line, 1,
            "lock-order cycle: " + " -> ".join(cycle + [cycle[0]])
            + " — two threads taking these in opposite order deadlock"))
    yield from state.findings


def _find_path(adj: Dict[str, Set[str]], src: str,
               dst: str) -> Optional[List[str]]:
    """BFS path src..dst (inclusive of src, exclusive of repeat of dst)."""
    if src == dst:
        return [src]
    parent: Dict[str, str] = {src: src}
    frontier = [src]
    while frontier:
        nxt: List[str] = []
        for u in frontier:
            for v in sorted(adj.get(u, ())):
                if v in parent:
                    continue
                parent[v] = u
                if v == dst:
                    path = [v]
                    while path[-1] != src:
                        path.append(parent[path[-1]])
                    return list(reversed(path))
                nxt.append(v)
        frontier = nxt
    return None


# ---------------------------------------------------------------------------
# VG004 — purity of hash_placed / key_sorted property readers
# ---------------------------------------------------------------------------
# A bare property read must never launch an exchange (round-4 advisor):
# exchange planners call _settle_placement() explicitly first. A reader
# that materializes turns an innocent `if rdd.hash_placed:` into device
# work — silently, at unpredictable times.

_VG004_READERS = {"hash_placed", "key_sorted"}
_VG004_IMPURE_CALLS = {
    "_settle_placement", "_materialize", "block", "collect", "to_numpy",
    "device_get", "host_get", "compute", "splits",
}
_VG004_IMPURE_ATTRS = {"counts_np", "num_rows"}


@rule("VG004", "hash_placed/key_sorted property readers must stay pure")
def vg004(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu"):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, _FUNC_DEFS)
                and node.name in _VG004_READERS):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _last_name(sub.func)
                if name in _VG004_IMPURE_CALLS:
                    yield Finding(
                        "VG004", ctx.display, sub.lineno,
                        sub.col_offset + 1,
                        f"'{node.name}' reader calls '{name}()' — "
                        "placement property reads are PURE; planners "
                        "call _settle_placement() explicitly (CLAUDE.md)")
            elif isinstance(sub, ast.Attribute) \
                    and sub.attr in _VG004_IMPURE_ATTRS:
                yield Finding(
                    "VG004", ctx.display, sub.lineno, sub.col_offset + 1,
                    f"'{node.name}' reader touches '.{sub.attr}' (device "
                    "materialization) — placement property reads are PURE")


# ---------------------------------------------------------------------------
# VG005 — blind broad excepts in distributed/ shuffle/ scheduler/
# ---------------------------------------------------------------------------
# A swallowed exception in the control plane turns a crash into a hang
# (the chaos harness exists because of these). Broad handlers must log or
# re-raise (typed VegaError included) — silence is the only failure.

_VG005_DIRS = (("vega_tpu", "distributed"), ("vega_tpu", "shuffle"),
               ("vega_tpu", "scheduler"))
_LOG_RECEIVERS = {"log", "logger", "logging"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(n, ast.Name)
               and n.id in ("Exception", "BaseException") for n in names)


@rule("VG005", "broad except that neither logs nor re-raises")
def vg005(ctx: FileCtx) -> Iterator[Finding]:
    if not any(ctx.in_dir(*d) for d in _VG005_DIRS):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.ExceptHandler)
                and _handler_is_broad(node)):
            continue
        ok = False
        for sub in _own_nodes(node):
            if isinstance(sub, ast.Raise):
                ok = True
                break
            if isinstance(sub, ast.Call):
                name = _last_name(sub.func)
                base = _base_name(sub.func)
                if (base in _LOG_RECEIVERS and name in _LOG_METHODS) \
                        or (base == "warnings" and name == "warn") \
                        or (base == "traceback"
                            and name == "print_exc"):
                    ok = True
                    break
        if not ok:
            yield Finding(
                "VG005", ctx.display, node.lineno, node.col_offset + 1,
                "broad except swallows the error silently — log it or "
                "re-raise a typed VegaError (a swallowed control-plane "
                "exception turns a crash into a hang)")


# ---------------------------------------------------------------------------
# VG006 — traced-code hazards in tpu/
# ---------------------------------------------------------------------------
# Inside jit/shard_map-traced code, .item(), int()/bool() on a traced
# value, and nonzero/unique without static size= are ConcretizationError
# tracebacks at best and silent recompiles/dynamic shapes at worst.

_TRACED_FILES = ("tpu/kernels.py", "tpu/pallas_kernels.py")
_TRACER_NAMES = {"shard_map", "jit", "pallas_call", "_shard_program"}
_SIZED_OPS = {"nonzero", "unique", "argwhere", "flatnonzero"}
_ARRAY_MODULES = ("jax.", "numpy.")


def _is_array_expr(node: ast.AST, ctx: FileCtx) -> bool:
    """Heuristic: a Compare, or a call into jax/numpy, or a method call on
    an array-ish receiver — the expressions whose int()/bool() coercion
    concretizes a tracer."""
    if isinstance(node, ast.Compare):
        return True
    if isinstance(node, ast.Call):
        qual = ctx.qualified(node.func)
        if qual and (qual.startswith(_ARRAY_MODULES)
                     or qual.startswith("jnp.")):
            return True
        if isinstance(node.func, ast.Attribute) and _last_name(
                node.func) in ("any", "all", "sum", "max", "min"):
            return True
    return False


def _traced_nodes(ctx: FileCtx) -> List[ast.AST]:
    traced: List[ast.AST] = []
    names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and _last_name(node.func) in _TRACER_NAMES:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                elif isinstance(arg, (ast.Lambda,)):
                    traced.append(arg)
    module_level = any(ctx.endswith(f) for f in _TRACED_FILES)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, _FUNC_DEFS):
            continue
        decorated = any(_last_name(d.func if isinstance(d, ast.Call) else d)
                        in ("jit", "pallas_call")
                        for d in node.decorator_list)
        if node.name in names or decorated \
                or (module_level and node in ctx.tree.body):
            traced.append(node)
    return traced


@rule("VG006", "traced-code hazards (.item / int()/bool() / unsized "
      "nonzero) in tpu/")
def vg006(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu", "tpu"):
        return
    seen: Set[int] = set()
    for root in _traced_nodes(ctx):
        for sub in ast.walk(root):
            if id(sub) in seen or not isinstance(sub, ast.Call):
                continue
            seen.add(id(sub))
            name = _last_name(sub.func)
            if name == "item":
                yield Finding(
                    "VG006", ctx.display, sub.lineno, sub.col_offset + 1,
                    ".item() inside traced code concretizes the tracer — "
                    "host-side folds belong outside the shard program")
            elif isinstance(sub.func, ast.Name) \
                    and sub.func.id in ("int", "bool", "float") \
                    and sub.args and _is_array_expr(sub.args[0], ctx):
                yield Finding(
                    "VG006", ctx.display, sub.lineno, sub.col_offset + 1,
                    f"{sub.func.id}() on a traced expression — use "
                    "lax.cond/where; Python coercion breaks under jit")
            elif name in _SIZED_OPS and not _kw(sub, "size"):
                qual = ctx.qualified(sub.func) or ""
                if qual.startswith(_ARRAY_MODULES) \
                        or qual.startswith("jnp."):
                    yield Finding(
                        "VG006", ctx.display, sub.lineno,
                        sub.col_offset + 1,
                        f"'{name}' without static size= in traced code — "
                        "dynamic output shape cannot compile (static "
                        "shapes everywhere: CLAUDE.md invariant)")


# ---------------------------------------------------------------------------
# VG007 — pool starvation: blocking on a shared executor from inside it
# ---------------------------------------------------------------------------
# nproc=1 here: pools run one thread per task, so a task that submits to
# its own pool and blocks on the Future waits on work queued behind
# itself. Draining a pool you created locally is fine; blocking on a
# shared/ambient pool's Future is the hazard.

_POOL_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}


@rule("VG007", "submit + blocking wait on a shared executor in one "
      "function")
def vg007(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu"):
        return
    for fn in [n for n in ast.walk(ctx.tree) if isinstance(n, _FUNC_DEFS)]:
        local_pools: Set[str] = set()
        submits: List[Tuple[int, int, str]] = []
        waits: List[Tuple[int, int, str]] = []
        own = list(_own_nodes(fn))
        # Pass 1: pools this function creates itself (draining those is
        # legal — the deadlock needs the pool to be shared).
        for sub in own:
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call) \
                    and _last_name(sub.value.func) in _POOL_CTORS:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        local_pools.add(t.id)
            if isinstance(sub, ast.withitem) \
                    and isinstance(sub.context_expr, ast.Call) \
                    and _last_name(sub.context_expr.func) in _POOL_CTORS \
                    and isinstance(sub.optional_vars, ast.Name):
                local_pools.add(sub.optional_vars.id)
        for sub in own:
            if not isinstance(sub, ast.Call):
                continue
            name = _last_name(sub.func)
            if name == "submit" and isinstance(sub.func, ast.Attribute):
                base = _base_name(sub.func)
                if base not in local_pools:
                    submits.append((sub.lineno, sub.col_offset + 1,
                                    base or "?"))
            elif name == "result" and not _kw(sub, "timeout") \
                    and not sub.args:
                waits.append((sub.lineno, sub.col_offset + 1,
                              "Future.result()"))
            elif name == "as_completed" or (
                    name == "wait"
                    and (ctx.qualified(sub.func) or "").endswith(
                        "futures.wait")
                    and not _kw(sub, "timeout")):
                waits.append((sub.lineno, sub.col_offset + 1, name))
        if submits and waits:
            line, col, desc = waits[0]
            yield Finding(
                "VG007", ctx.display, line, col,
                f"blocking {desc} in a function that also submits to "
                f"shared executor '{submits[0][2]}' — on the 1-thread-"
                "per-task pool this starves (task waits on work queued "
                "behind itself); drain a locally-created pool instead")


# ---------------------------------------------------------------------------
# VG008 — DAG scheduler job entries must route through the job server
# ---------------------------------------------------------------------------
# Since PR 7 every job — blocking or async — goes through
# scheduler/jobserver.py so fair-scheduling pools, per-pool quotas, and
# cancellation apply uniformly. A direct DAGScheduler.run_job /
# run_job_with_listener / _run_job_inner call anywhere else silently
# bypasses the arbiter: that job's tasks go straight to the backend,
# monopolizing slots no quota can reclaim. Allowed callers: context.py
# (the public facade), rdd/ (actions call context.run_job — a Context
# method, not the scheduler's), jobserver.py (the route itself), and
# scheduler/dag.py (the implementation's own internals).

_VG008_ALLOWED_SUFFIXES = (
    "vega_tpu/context.py",
    "vega_tpu/scheduler/dag.py",
    "vega_tpu/scheduler/jobserver.py",
)
_VG008_ENTRIES = {"run_job", "run_job_with_listener"}


@rule("VG008", "DAGScheduler job entry called outside the job-server route")
def vg008(ctx: FileCtx) -> Iterator[Finding]:
    if any(ctx.endswith(s) for s in _VG008_ALLOWED_SUFFIXES) \
            or ctx.in_dir("vega_tpu", "rdd"):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr == "_run_job_inner":
            yield Finding(
                "VG008", ctx.display, node.lineno, node.col_offset + 1,
                "_run_job_inner is the job server's private entry — "
                "submit through Context.submit_job/run_job so pools, "
                "quotas and cancellation apply (docs/LINTING.md VG008)")
            continue
        if attr not in _VG008_ENTRIES:
            continue
        # Only scheduler-shaped receivers: `self.scheduler.run_job`,
        # `ctx.scheduler.run_job`, a local named `scheduler`, or a direct
        # `DAGScheduler(...)` construction. Context.run_job (the facade
        # that DOES route through the server) stays legal everywhere.
        recv = node.func.value
        qual = (ctx.qualified(recv) or "").lower()
        last = ""
        if isinstance(recv, ast.Attribute):
            last = recv.attr
        elif isinstance(recv, ast.Name):
            last = recv.id
        ctor = _last_name(recv.func) if isinstance(recv, ast.Call) else None
        if "scheduler" in qual or "scheduler" in last.lower() \
                or ctor == "DAGScheduler":
            yield Finding(
                "VG008", ctx.display, node.lineno, node.col_offset + 1,
                f"direct DAGScheduler.{attr} call bypasses the job "
                "server (no pool/quota arbitration, no cancellation) — "
                "route through Context.submit_job/run_job")
