"""vegalint rules VG001–VG020: the project invariants as AST checks.

Each rule encodes one CLAUDE.md invariant (see docs/LINTING.md for the
catalog with rationale and examples). Rules are deliberately conservative:
a rule that cries wolf gets pragma'd into silence, and then the invariant
is unguarded again — so every heuristic here is tuned to the failure mode
that actually bit this repo, not to theoretical completeness. The dynamic
complement (vega_tpu/lint/sync_witness.py) covers what lexical analysis
cannot see at runtime.

VG001–VG008 are the per-file (and lock-graph) invariants from PRs 3 and
7; VG013 (PR 11) keeps frame planning pure — no materialization at
plan-build time; VG014 (PR 13) holds every exchange implementation to
the (cols, count, overflow) / n_shards==1 contract the collective-aware
planner relies on; VG015 (PR 16) funnels streaming state mutation
through the exactly-once commit API (streaming/state.py) — and VG012's
index extends into streaming/ so receiver socket reads stay bounded.
VG009–VG012 are the cross-process CONTRACT rules: a
shared per-file
index pass (``_contract_extract``, cached by the engine) reduces each
file to its protocol/config/event surfaces, and global combines join
the index — every sent msg_type has a dispatch arm and vice versa
(VG009), every worker-side Configuration read is propagated to spawned/
ssh workers and every VEGA_TPU_* literal resolves (VG010), every
listener field read exists on the event schema and every emitted event
is aggregated (VG011), and no cross-process socket op waits unbounded
(VG012).

VG016–VG019 (PR 18) are the thread-role dataflow rules: a per-file
call-graph extraction (vega_tpu/lint/callgraph.py, cached under
extract_key="callgraph" like the contract index) combines into a
project-wide call graph with roles propagated from the declared role map
— no blocking op reachable from a latency-critical role (VG016), no
driver-only state captured into executor-shipped closures (VG017), no
leaked socket/file handles on cross-process paths (VG018), and no
driver-only function reachable from a confined worker/receiver role
(VG019). Implementations live in callgraph.py; registration is here so
one import populates the whole registry.

VG020 (PR 20) guards the string-column invariant: device-tier code
(vega_tpu/tpu/) must never create object-dtype numpy arrays — strings
cross the device boundary only as int32 dictionary codes
(tpu/dict_encoding.py, the one exempt file).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from vega_tpu.lint.engine import FileCtx, Finding, rule

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _last_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _base_name(node: ast.AST) -> Optional[str]:
    """Leftmost identifier of an attribute chain (`a.b.c()` -> 'a')."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


def _own_nodes(root: ast.AST) -> Iterator[ast.AST]:
    """Descendants of `root` excluding nested function/lambda subtrees —
    the code that actually runs when `root`'s body runs."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# VG001 — raw jax spellings that must go through tpu/compat.py
# ---------------------------------------------------------------------------
# jax.shard_map / jax.enable_x64 / jax.export do not exist on jax < 0.5 and
# lax.platform_dependent lowers every branch there; writing any of them
# directly wiped out the entire dense tier at seed (fixed in PR 1 by
# vega_tpu/tpu/compat.py). Only compat.py may touch the raw surface.

_VG001_BANNED = (
    "jax.shard_map",
    "jax.enable_x64",
    "jax.export",
    "jax.lax.platform_dependent",
    "jax.experimental.shard_map",
    "jax.experimental.enable_x64",
)


def _banned_prefix(qual: Optional[str]) -> Optional[str]:
    if qual is None:
        return None
    for b in _VG001_BANNED:
        if qual == b or qual.startswith(b + "."):
            return b
    return None


@rule("VG001", "raw jax compat-surface spelling outside tpu/compat.py")
def vg001(ctx: FileCtx) -> Iterator[Finding]:
    if ctx.endswith("tpu/compat.py"):
        return
    if "jax" not in ctx.source:
        return  # no alias can reach jax.* without the literal appearing
    # Import sites: `from jax.experimental.shard_map import ...`,
    # `from jax import export`, `from jax.lax import platform_dependent`.
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.level == 0 \
                and node.module:
            for a in node.names:
                b = _banned_prefix(f"{node.module}.{a.name}")
                if b:
                    yield Finding(
                        "VG001", ctx.display, node.lineno,
                        node.col_offset + 1,
                        f"import of {node.module}.{a.name}: use "
                        "vega_tpu.tpu.compat (jax<0.5 has a different "
                        "surface — this exact drift wiped the dense tier "
                        "at seed)")
    # Use sites: outermost Name/Attribute chains whose alias-expanded
    # dotted name lands on the banned surface.
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Attribute, ast.Name)):
            continue
        if isinstance(parents.get(node), ast.Attribute):
            continue  # inner link of a longer chain; outermost reports
        if isinstance(node, ast.Name) and not isinstance(
                node.ctx, ast.Load):
            continue
        qual = ctx.qualified(node)
        b = _banned_prefix(qual)
        if b:
            yield Finding(
                "VG001", ctx.display, node.lineno, node.col_offset + 1,
                f"raw '{qual}' — use the vega_tpu.tpu.compat shim "
                "(CLAUDE.md: ALL dense-tier code goes through compat.py)")


# ---------------------------------------------------------------------------
# VG002 — device probes reachable at module import time
# ---------------------------------------------------------------------------
# jax.devices()/default_backend() initialize the backend; on a wedged axon
# tunnel that call hangs forever, so CLAUDE.md bans it from import paths
# (and conftest's forced CPU mesh must run before any backend init).

_VG002_PROBES = {
    "jax.devices",
    "jax.default_backend",
    "jax.local_devices",
    "jax.device_count",
}


def _is_main_guard(test: ast.AST) -> bool:
    return (isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == "__name__"
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value == "__main__")


@rule("VG002", "device probe reachable at module import time")
def vg002(ctx: FileCtx) -> Iterator[Finding]:
    if "jax" not in ctx.source:
        return  # probes are jax.* calls; cheap gate saves the deep walk
    # Local functions that probe: a module-level call to one of them is
    # just as import-hanging as the probe itself (one hop, same module).
    probe_funcs: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_DEFS):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and ctx.qualified(sub.func) in _VG002_PROBES:
                    probe_funcs.add(node.name)
                    break

    findings: List[Finding] = []

    def walk(node: ast.AST, import_time: bool) -> None:
        if isinstance(node, _FUNC_DEFS):
            # Decorators and argument defaults DO run at import time;
            # the body does not.
            for d in node.decorator_list:
                walk(d, import_time)
            for d in list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None]:
                walk(d, import_time)
            for b in node.body:
                walk(b, False)
            return
        if isinstance(node, ast.Lambda):
            walk(node.body, False)
            return
        if isinstance(node, ast.If) and _is_main_guard(node.test):
            # `if __name__ == "__main__":` runs as a script entry, not on
            # import — but its ELSE branch is exactly what runs on import.
            for b in node.body:
                walk(b, False)
            for b in node.orelse:
                walk(b, import_time)
            return
        if import_time and isinstance(node, ast.Call):
            qual = ctx.qualified(node.func)
            if qual in _VG002_PROBES:
                findings.append(Finding(
                    "VG002", ctx.display, node.lineno, node.col_offset + 1,
                    f"'{qual}()' runs at module import time — backend "
                    "init on an import path hangs forever on a wedged "
                    "device tunnel (CLAUDE.md environment quirk)"))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in probe_funcs:
                findings.append(Finding(
                    "VG002", ctx.display, node.lineno, node.col_offset + 1,
                    f"module-level call to '{node.func.id}()', which "
                    "probes jax devices — backend init on an import path "
                    "hangs on a wedged tunnel"))
        for child in ast.iter_child_nodes(node):
            walk(child, import_time)

    walk(ctx.tree, True)
    yield from findings


# ---------------------------------------------------------------------------
# VG003 — lock-order graph: cycles + blocking calls under cache/store locks
# ---------------------------------------------------------------------------
# The seed suite froze on exactly this: two task threads interleaving
# device slicing + device_get deadlocked old XLA:CPU on the 1-core box.
# The rule builds the acquisition graph over threading.Lock/RLock (and
# sync_witness.named_lock) attributes across vega_tpu/, flags cycles, and
# flags blocking calls (device_get/host_get, socket recv, Future.result,
# queue.get without timeout) made while holding _host_cache_lock or any
# cache/store lock. Lexical nesting plus one resolvable call hop; the
# runtime sync_witness covers dynamic orders statically invisible here.

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_RLOCK_CTORS = {"threading.RLock"}


def _lock_ctor(call: ast.AST, ctx: FileCtx) -> Optional[bool]:
    """None if not a lock constructor; else True when reentrant."""
    if not isinstance(call, ast.Call):
        return None
    qual = ctx.qualified(call.func)
    if qual in _LOCK_CTORS:
        return qual in _RLOCK_CTORS
    if _last_name(call.func) == "named_lock":
        for k in call.keywords:
            if k.arg == "reentrant" and isinstance(k.value, ast.Constant):
                return bool(k.value.value)
        return False
    return None


# The analysis runs in two cacheable passes (engine.py result cache):
# `_vg003_extract` reduces one file to plain data — lock definitions plus
# acquisition/call/blocking sites whose lock operands are DESCRIPTORS
# (unresolved references) — and the project-wide combine resolves
# descriptors against the global lock set, builds the acquisition graph,
# and reports cycles. Descriptors defer exactly the lookups that need
# other files' lock definitions (imported locks, foreign attributes), so
# per-file extraction stays byte-stable while the rest of the tree
# changes.


def _vg003_desc(expr: ast.AST, ctx: FileCtx,
                cls: Optional[str]) -> Optional[tuple]:
    """Unresolved lock reference for a with-item / acquire operand."""
    if isinstance(expr, ast.Name):
        return ("name", ctx.module, expr.id, ctx.aliases.get(expr.id))
    if isinstance(expr, ast.Attribute):
        base = _base_name(expr)
        if base == "self" and isinstance(expr.value, ast.Name):
            return ("self", ctx.module, cls, expr.attr)
        return ("attr", ctx.qualified(expr), expr.attr, ctx.module)
    return None


def _vg003_resolve(desc: Optional[tuple],
                   locks: Dict[str, bool]) -> Optional[str]:
    """Descriptor -> lock key, given every file's lock definitions."""
    if desc is None:
        return None
    kind = desc[0]
    if kind == "name":
        _, module, name, alias = desc
        key = f"{module}.{name}"
        if key in locks:
            return key
        if alias and alias in locks:
            return alias
        return key if "lock" in name.lower() else None
    if kind == "self":
        _, module, cls, attr = desc
        if cls is None:
            return None
        key = f"{module}.{cls}.{attr}"
        return key if (key in locks or "lock" in attr.lower()) else None
    _, qual, attr, module = desc
    if qual and qual in locks:
        return qual
    if "lock" in attr.lower():
        return f"{module}.?.{attr}"  # opaque foreign lock
    return None


_CACHEISH = ("cache", "store")


def _is_cacheish(key: str) -> bool:
    low = key.lower()
    return any(s in low for s in _CACHEISH)


def _blocking_desc(call: ast.Call) -> Optional[str]:
    name = _last_name(call.func)
    if name in ("device_get", "host_get"):
        return f"{name}() (a driver<->device round trip)"
    if name == "recv":
        return "socket recv()"
    if name == "result" and not call.args and not _kw(call, "timeout"):
        return "Future.result() without timeout"
    if name == "get" and isinstance(call.func, ast.Attribute) \
            and not call.args and not _kw(call, "timeout"):
        recv = _base_name(call.func) or ""
        attr_chain = call.func.value
        attr = attr_chain.attr if isinstance(attr_chain, ast.Attribute) \
            else recv
        if "queue" in (attr or "").lower() or "queue" in recv.lower():
            return "queue get() without timeout"
    return None


def _vg003_scan_fn(body: List[ast.stmt], ctx: FileCtx, cls: Optional[str],
                   fname: str, data: dict) -> None:
    direct: List[tuple] = []
    nested: List[Tuple[List[ast.stmt], Optional[str], str]] = []

    def walk(node: ast.AST, held: List[tuple]) -> None:
        if isinstance(node, _FUNC_DEFS):
            nested.append((node.body, cls, node.name))
            return  # a nested def runs later, not under the held locks
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.With):
            here: List[tuple] = []
            for item in node.items:
                walk(item.context_expr, held + here)
                desc = _vg003_desc(item.context_expr, ctx, cls)
                if desc is None:
                    continue
                data["acquires"].append(
                    (held + here, desc, item.context_expr.lineno))
                here = here + [desc]
                direct.append(desc)
            for b in node.body:
                walk(b, held + here)
            return
        if isinstance(node, ast.Call):
            desc = _blocking_desc(node)
            if desc and held:
                data["blocking"].append(
                    (desc, list(held), node.lineno, node.col_offset + 1))
            if held:
                callee: Optional[Tuple] = None
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self" and cls:
                    callee = (ctx.module, cls, f.attr)
                elif isinstance(f, ast.Name):
                    callee = (ctx.module, None, f.id)
                if callee is not None:
                    data["calls"].append(
                        (list(held), callee, node.lineno))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in body:
        walk(stmt, [])
    data["fn_locks"].setdefault((ctx.module, cls, fname),
                                []).extend(direct)
    for nbody, ncls, nname in nested:
        _vg003_scan_fn(nbody, ctx, ncls, nname, data)


def _vg003_extract(ctx: FileCtx) -> Optional[dict]:
    """Per-file half of VG003: lock definitions + unresolved acquisition/
    call/blocking sites (cached by the engine; combine resolves them)."""
    if not ctx.in_dir("vega_tpu"):
        return None
    data: dict = {"locks": {}, "acquires": [], "fn_locks": {},
                  "calls": [], "blocking": []}
    # Lock definitions (module-level names and self.X attributes).
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) \
                and isinstance(node.targets[0], ast.Name):
            r = _lock_ctor(node.value, ctx)
            if r is not None:
                data["locks"][f"{ctx.module}.{node.targets[0].id}"] = r
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            r = _lock_ctor(sub.value, ctx)
            if r is None:
                continue
            t = sub.targets[0]
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                data["locks"][f"{ctx.module}.{node.name}.{t.attr}"] = r
            elif isinstance(t, ast.Name):  # class-body lock (Env._lock)
                data["locks"][f"{ctx.module}.{node.name}.{t.id}"] = r
    # Acquisitions — module body, functions, methods.
    _vg003_scan_fn(
        [s for s in ctx.tree.body
         if not isinstance(s, _FUNC_DEFS + (ast.ClassDef,))],
        ctx, None, "<module>", data)
    for node in ctx.tree.body:
        if isinstance(node, _FUNC_DEFS):
            _vg003_scan_fn(node.body, ctx, None, node.name, data)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, _FUNC_DEFS):
                    _vg003_scan_fn(sub.body, ctx, node.name,
                                   sub.name, data)
    if not (data["locks"] or data["acquires"] or data["calls"]
            or data["blocking"]):
        return None
    return data


@rule("VG003", "lock-order cycles and blocking calls under cache/store "
      "locks", project=True, extract=_vg003_extract)
def vg003(records: List[Tuple[str, dict]]) -> Iterator[Finding]:
    # Pass 1: the global lock set (descriptor resolution needs it).
    locks: Dict[str, bool] = {}
    for _display, data in records:
        locks.update(data["locks"])
    findings: List[Finding] = []
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    fn_locks: Dict[Tuple, Set[str]] = {}
    # Pass 2: resolve acquisition sites into graph edges + blocking
    # findings, in file order (first site wins, as before the split).
    for display, data in records:
        for held_descs, desc, line in data["acquires"]:
            key = _vg003_resolve(desc, locks)
            if key is None:
                continue
            for h_desc in held_descs:
                h = _vg003_resolve(h_desc, locks)
                if h is None:
                    continue
                if h == key and locks.get(key):
                    continue  # reentrant re-acquire is fine
                edges.setdefault((h, key), (display, line))
        for fn_key, descs in data["fn_locks"].items():
            fn_locks.setdefault(fn_key, set()).update(
                k for k in (_vg003_resolve(d, locks) for d in descs)
                if k is not None)
        for desc_text, held_descs, line, col in data["blocking"]:
            held = [k for k in (_vg003_resolve(d, locks)
                                for d in held_descs) if k is not None]
            cacheish = [h for h in held if _is_cacheish(h)]
            if cacheish:
                findings.append(Finding(
                    "VG003", display, line, col,
                    f"blocking {desc_text} while holding cache/store lock "
                    f"'{cacheish[-1]}' — can deadlock or starve the "
                    "1-core sandbox (the seed-suite XLA:CPU wedge)"))
    # Pass 3: one call hop — held locks flow into the callee's direct set.
    for display, data in records:
        for held_descs, callee, line in data["calls"]:
            held = [k for k in (_vg003_resolve(d, locks)
                                for d in held_descs) if k is not None]
            if not held:
                continue
            for key in fn_locks.get(tuple(callee), ()):
                for h in held:
                    if h == key and locks.get(key):
                        continue
                    edges.setdefault((h, key), (display, line))
    # Pass 4: cycles (including non-reentrant self-acquisition).
    adj: Dict[str, Set[str]] = {}
    for (a, b), _site in edges.items():
        adj.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()
    for (a, b), (display, line) in sorted(edges.items(),
                                          key=lambda kv: kv[1]):
        if a == b:
            findings.append(Finding(
                "VG003", display, line, 1,
                f"non-reentrant lock '{a}' re-acquired while already "
                "held — self-deadlock"))
            continue
        path = _find_path(adj, b, a)
        if path is None:
            continue
        cycle = [a] + path[:-1]  # path ends at a; drop the repeat
        lo = cycle.index(min(cycle))
        canon = tuple(cycle[lo:] + cycle[:lo])
        if canon in seen_cycles:
            continue
        seen_cycles.add(canon)
        findings.append(Finding(
            "VG003", display, line, 1,
            "lock-order cycle: " + " -> ".join(cycle + [cycle[0]])
            + " — two threads taking these in opposite order deadlock"))
    yield from findings


def _find_path(adj: Dict[str, Set[str]], src: str,
               dst: str) -> Optional[List[str]]:
    """BFS path src..dst (inclusive of src, exclusive of repeat of dst)."""
    if src == dst:
        return [src]
    parent: Dict[str, str] = {src: src}
    frontier = [src]
    while frontier:
        nxt: List[str] = []
        for u in frontier:
            for v in sorted(adj.get(u, ())):
                if v in parent:
                    continue
                parent[v] = u
                if v == dst:
                    path = [v]
                    while path[-1] != src:
                        path.append(parent[path[-1]])
                    return list(reversed(path))
                nxt.append(v)
        frontier = nxt
    return None


# ---------------------------------------------------------------------------
# VG004 — purity of hash_placed / key_sorted property readers
# ---------------------------------------------------------------------------
# A bare property read must never launch an exchange (round-4 advisor):
# exchange planners call _settle_placement() explicitly first. A reader
# that materializes turns an innocent `if rdd.hash_placed:` into device
# work — silently, at unpredictable times.

_VG004_READERS = {"hash_placed", "key_sorted"}
_VG004_IMPURE_CALLS = {
    "_settle_placement", "_materialize", "block", "collect", "to_numpy",
    "device_get", "host_get", "compute", "splits",
}
_VG004_IMPURE_ATTRS = {"counts_np", "num_rows"}


@rule("VG004", "hash_placed/key_sorted property readers must stay pure")
def vg004(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu"):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, _FUNC_DEFS)
                and node.name in _VG004_READERS):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _last_name(sub.func)
                if name in _VG004_IMPURE_CALLS:
                    yield Finding(
                        "VG004", ctx.display, sub.lineno,
                        sub.col_offset + 1,
                        f"'{node.name}' reader calls '{name}()' — "
                        "placement property reads are PURE; planners "
                        "call _settle_placement() explicitly (CLAUDE.md)")
            elif isinstance(sub, ast.Attribute) \
                    and sub.attr in _VG004_IMPURE_ATTRS:
                yield Finding(
                    "VG004", ctx.display, sub.lineno, sub.col_offset + 1,
                    f"'{node.name}' reader touches '.{sub.attr}' (device "
                    "materialization) — placement property reads are PURE")


# ---------------------------------------------------------------------------
# VG005 — blind broad excepts in distributed/ shuffle/ scheduler/
# ---------------------------------------------------------------------------
# A swallowed exception in the control plane turns a crash into a hang
# (the chaos harness exists because of these). Broad handlers must log or
# re-raise (typed VegaError included) — silence is the only failure.

_VG005_DIRS = (("vega_tpu", "distributed"), ("vega_tpu", "shuffle"),
               ("vega_tpu", "scheduler"))
_LOG_RECEIVERS = {"log", "logger", "logging"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(n, ast.Name)
               and n.id in ("Exception", "BaseException") for n in names)


@rule("VG005", "broad except that neither logs nor re-raises")
def vg005(ctx: FileCtx) -> Iterator[Finding]:
    if not any(ctx.in_dir(*d) for d in _VG005_DIRS):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.ExceptHandler)
                and _handler_is_broad(node)):
            continue
        ok = False
        for sub in _own_nodes(node):
            if isinstance(sub, ast.Raise):
                ok = True
                break
            if isinstance(sub, ast.Call):
                name = _last_name(sub.func)
                base = _base_name(sub.func)
                if (base in _LOG_RECEIVERS and name in _LOG_METHODS) \
                        or (base == "warnings" and name == "warn") \
                        or (base == "traceback"
                            and name == "print_exc"):
                    ok = True
                    break
        if not ok:
            yield Finding(
                "VG005", ctx.display, node.lineno, node.col_offset + 1,
                "broad except swallows the error silently — log it or "
                "re-raise a typed VegaError (a swallowed control-plane "
                "exception turns a crash into a hang)")


# ---------------------------------------------------------------------------
# VG006 — traced-code hazards in tpu/
# ---------------------------------------------------------------------------
# Inside jit/shard_map-traced code, .item(), int()/bool() on a traced
# value, and nonzero/unique without static size= are ConcretizationError
# tracebacks at best and silent recompiles/dynamic shapes at worst.

_TRACED_FILES = ("tpu/kernels.py", "tpu/pallas_kernels.py")
_TRACER_NAMES = {"shard_map", "jit", "pallas_call", "_shard_program"}
_SIZED_OPS = {"nonzero", "unique", "argwhere", "flatnonzero"}
_ARRAY_MODULES = ("jax.", "numpy.")


def _is_array_expr(node: ast.AST, ctx: FileCtx) -> bool:
    """Heuristic: a Compare, or a call into jax/numpy, or a method call on
    an array-ish receiver — the expressions whose int()/bool() coercion
    concretizes a tracer."""
    if isinstance(node, ast.Compare):
        return True
    if isinstance(node, ast.Call):
        qual = ctx.qualified(node.func)
        if qual and (qual.startswith(_ARRAY_MODULES)
                     or qual.startswith("jnp.")):
            return True
        if isinstance(node.func, ast.Attribute) and _last_name(
                node.func) in ("any", "all", "sum", "max", "min"):
            return True
    return False


def _traced_nodes(ctx: FileCtx) -> List[ast.AST]:
    traced: List[ast.AST] = []
    names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and _last_name(node.func) in _TRACER_NAMES:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                elif isinstance(arg, (ast.Lambda,)):
                    traced.append(arg)
    module_level = any(ctx.endswith(f) for f in _TRACED_FILES)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, _FUNC_DEFS):
            continue
        decorated = any(_last_name(d.func if isinstance(d, ast.Call) else d)
                        in ("jit", "pallas_call")
                        for d in node.decorator_list)
        if node.name in names or decorated \
                or (module_level and node in ctx.tree.body):
            traced.append(node)
    return traced


@rule("VG006", "traced-code hazards (.item / int()/bool() / unsized "
      "nonzero) in tpu/")
def vg006(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu", "tpu"):
        return
    seen: Set[int] = set()
    for root in _traced_nodes(ctx):
        for sub in ast.walk(root):
            if id(sub) in seen or not isinstance(sub, ast.Call):
                continue
            seen.add(id(sub))
            name = _last_name(sub.func)
            if name == "item":
                yield Finding(
                    "VG006", ctx.display, sub.lineno, sub.col_offset + 1,
                    ".item() inside traced code concretizes the tracer — "
                    "host-side folds belong outside the shard program")
            elif isinstance(sub.func, ast.Name) \
                    and sub.func.id in ("int", "bool", "float") \
                    and sub.args and _is_array_expr(sub.args[0], ctx):
                yield Finding(
                    "VG006", ctx.display, sub.lineno, sub.col_offset + 1,
                    f"{sub.func.id}() on a traced expression — use "
                    "lax.cond/where; Python coercion breaks under jit")
            elif name in _SIZED_OPS and not _kw(sub, "size"):
                qual = ctx.qualified(sub.func) or ""
                if qual.startswith(_ARRAY_MODULES) \
                        or qual.startswith("jnp."):
                    yield Finding(
                        "VG006", ctx.display, sub.lineno,
                        sub.col_offset + 1,
                        f"'{name}' without static size= in traced code — "
                        "dynamic output shape cannot compile (static "
                        "shapes everywhere: CLAUDE.md invariant)")


# ---------------------------------------------------------------------------
# VG007 — pool starvation: blocking on a shared executor from inside it
# ---------------------------------------------------------------------------
# nproc=1 here: pools run one thread per task, so a task that submits to
# its own pool and blocks on the Future waits on work queued behind
# itself. Draining a pool you created locally is fine; blocking on a
# shared/ambient pool's Future is the hazard.

_POOL_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}


@rule("VG007", "submit + blocking wait on a shared executor in one "
      "function")
def vg007(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu"):
        return
    for fn in [n for n in ast.walk(ctx.tree) if isinstance(n, _FUNC_DEFS)]:
        local_pools: Set[str] = set()
        submits: List[Tuple[int, int, str]] = []
        waits: List[Tuple[int, int, str]] = []
        own = list(_own_nodes(fn))
        # Pass 1: pools this function creates itself (draining those is
        # legal — the deadlock needs the pool to be shared).
        for sub in own:
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call) \
                    and _last_name(sub.value.func) in _POOL_CTORS:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        local_pools.add(t.id)
            if isinstance(sub, ast.withitem) \
                    and isinstance(sub.context_expr, ast.Call) \
                    and _last_name(sub.context_expr.func) in _POOL_CTORS \
                    and isinstance(sub.optional_vars, ast.Name):
                local_pools.add(sub.optional_vars.id)
        for sub in own:
            if not isinstance(sub, ast.Call):
                continue
            name = _last_name(sub.func)
            if name == "submit" and isinstance(sub.func, ast.Attribute):
                base = _base_name(sub.func)
                if base not in local_pools:
                    submits.append((sub.lineno, sub.col_offset + 1,
                                    base or "?"))
            elif name == "result" and not _kw(sub, "timeout") \
                    and not sub.args:
                waits.append((sub.lineno, sub.col_offset + 1,
                              "Future.result()"))
            elif name == "as_completed" or (
                    name == "wait"
                    and (ctx.qualified(sub.func) or "").endswith(
                        "futures.wait")
                    and not _kw(sub, "timeout")):
                waits.append((sub.lineno, sub.col_offset + 1, name))
        if submits and waits:
            line, col, desc = waits[0]
            yield Finding(
                "VG007", ctx.display, line, col,
                f"blocking {desc} in a function that also submits to "
                f"shared executor '{submits[0][2]}' — on the 1-thread-"
                "per-task pool this starves (task waits on work queued "
                "behind itself); drain a locally-created pool instead")


# ---------------------------------------------------------------------------
# VG008 — DAG scheduler job entries must route through the job server
# ---------------------------------------------------------------------------
# Since PR 7 every job — blocking or async — goes through
# scheduler/jobserver.py so fair-scheduling pools, per-pool quotas, and
# cancellation apply uniformly. A direct DAGScheduler.run_job /
# run_job_with_listener / _run_job_inner call anywhere else silently
# bypasses the arbiter: that job's tasks go straight to the backend,
# monopolizing slots no quota can reclaim. Allowed callers: context.py
# (the public facade), rdd/ (actions call context.run_job — a Context
# method, not the scheduler's), jobserver.py (the route itself), and
# scheduler/dag.py (the implementation's own internals).

_VG008_ALLOWED_SUFFIXES = (
    "vega_tpu/context.py",
    "vega_tpu/scheduler/dag.py",
    "vega_tpu/scheduler/jobserver.py",
)
_VG008_ENTRIES = {"run_job", "run_job_with_listener"}


@rule("VG008", "DAGScheduler job entry called outside the job-server route")
def vg008(ctx: FileCtx) -> Iterator[Finding]:
    if any(ctx.endswith(s) for s in _VG008_ALLOWED_SUFFIXES) \
            or ctx.in_dir("vega_tpu", "rdd"):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr == "_run_job_inner":
            yield Finding(
                "VG008", ctx.display, node.lineno, node.col_offset + 1,
                "_run_job_inner is the job server's private entry — "
                "submit through Context.submit_job/run_job so pools, "
                "quotas and cancellation apply (docs/LINTING.md VG008)")
            continue
        if attr not in _VG008_ENTRIES:
            continue
        # Only scheduler-shaped receivers: `self.scheduler.run_job`,
        # `ctx.scheduler.run_job`, a local named `scheduler`, or a direct
        # `DAGScheduler(...)` construction. Context.run_job (the facade
        # that DOES route through the server) stays legal everywhere.
        recv = node.func.value
        qual = (ctx.qualified(recv) or "").lower()
        last = ""
        if isinstance(recv, ast.Attribute):
            last = recv.attr
        elif isinstance(recv, ast.Name):
            last = recv.id
        ctor = _last_name(recv.func) if isinstance(recv, ast.Call) else None
        if "scheduler" in qual or "scheduler" in last.lower() \
                or ctor == "DAGScheduler":
            yield Finding(
                "VG008", ctx.display, node.lineno, node.col_offset + 1,
                f"direct DAGScheduler.{attr} call bypasses the job "
                "server (no pool/quota arbitration, no cancellation) — "
                "route through Context.submit_job/run_job")


# ---------------------------------------------------------------------------
# Contract index — the shared per-file extraction behind VG009-VG011
# ---------------------------------------------------------------------------
# PRs 4-8 grew three cross-process contract surfaces: the framed-TCP
# message grammar (protocol.py), the Configuration -> env -> spawned/ssh
# worker knob pipeline (env.py + backend._worker_knobs), and the job-scoped
# event-bus schema (scheduler/events.py). Each is enforced only at runtime
# otherwise, and a typo in any of them is a silent cross-process wedge.
# One walk per file reduces the surfaces to plain data (cached by the
# engine); the rules below are global joins over that index.

_VG009_SEND_ARG = {"send_msg": 1, "encode_msg": 0, "_call": 0}
_VG009_DISPATCH_VARS = {"msg_type", "reply_type", "marker"}
_ENV_NAME_RE = re.compile(r"VEGA_TPU_[A-Z0-9_]*[A-Z0-9]")
# Infrastructure knobs that are deliberately NOT Configuration fields:
# the sync-witness switch, the hardware-test gate, and the lint engine's
# own cache override (docs/LINTING.md VG010).
_VG010_ALLOWLIST = {"VEGA_TPU_DEBUG_SYNC", "VEGA_TPU_HW_TESTS",
                    "VEGA_TPU_LINT_CACHE"}
_VG010_WORKER_SIDE = ("distributed/worker.py",
                      "distributed/shuffle_server.py")


def _docstring_ids(tree: ast.AST) -> Set[int]:
    """ids of docstring Constant nodes (module/class/function bodies)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef) + _FUNC_DEFS):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def _conf_receiver(node: ast.AST) -> bool:
    """True for conf / self.conf / env.conf / Env.get().conf receivers."""
    return (isinstance(node, ast.Name) and node.id == "conf") or \
        (isinstance(node, ast.Attribute) and node.attr == "conf")


def _event_reads_of(fn: ast.AST) -> List[tuple]:
    """Attribute loads on `event` inside an on_event listener, with
    isinstance narrowing: reads in the body (and test) of an
    `if isinstance(event, X):` are checked against X's fields only."""
    reads: List[tuple] = []

    def isinstance_classes(test: ast.AST) -> List[str]:
        found: List[str] = []
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) \
                    and _last_name(sub.func) == "isinstance" \
                    and len(sub.args) == 2 \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id == "event":
                t = sub.args[1]
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                found.extend(n for n in (_last_name(e) for e in elts) if n)
        return found

    def walk(node: ast.AST, narrow: Optional[tuple]) -> None:
        if isinstance(node, ast.If):
            classes = isinstance_classes(node.test)
            inner = tuple(classes) if classes else narrow
            walk_children(node.test, inner)
            for b in node.body:
                walk(b, inner)
            for b in node.orelse:
                walk(b, narrow)
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "event" \
                and isinstance(node.ctx, ast.Load):
            reads.append((node.attr, node.lineno, node.col_offset + 1,
                          narrow))
        walk_children(node, narrow)

    def walk_children(node: ast.AST, narrow: Optional[tuple]) -> None:
        for child in ast.iter_child_nodes(node):
            walk(child, narrow)

    for stmt in fn.body:
        walk(stmt, None)
    return reads


def _contract_extract(ctx: FileCtx) -> Optional[dict]:
    out: dict = {}
    docstrings = _docstring_ids(ctx.tree)

    # --- protocol sends + dispatch arms (the framed-TCP grammar) -------
    if ctx.in_dir("vega_tpu", "distributed"):
        sends: List[tuple] = []
        arms: List[tuple] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = _last_name(node.func)
                idx = _VG009_SEND_ARG.get(name)
                if name == "request":
                    idx = 2
                if idx is not None and len(node.args) > idx \
                        and isinstance(node.args[idx], ast.Constant) \
                        and isinstance(node.args[idx].value, str):
                    sends.append((node.args[idx].value, node.lineno,
                                  node.col_offset + 1))
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                for var, lit in ((node.left, node.comparators[0]),
                                 (node.comparators[0], node.left)):
                    if isinstance(var, ast.Name) \
                            and var.id in _VG009_DISPATCH_VARS \
                            and isinstance(lit, ast.Constant) \
                            and isinstance(lit.value, str):
                        arms.append((lit.value, node.lineno,
                                     node.col_offset + 1))
        if sends:
            out["sends"] = sends
        if arms:
            out["arms"] = arms

    # --- worker-side Configuration reads + the propagation list --------
    if ctx.in_dir("vega_tpu", "shuffle") \
            or any(ctx.endswith(s) for s in _VG010_WORKER_SIDE):
        reads: List[tuple] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and _conf_receiver(node.value):
                reads.append((node.attr, node.lineno, node.col_offset + 1))
            elif isinstance(node, ast.Call) \
                    and _last_name(node.func) == "getattr" \
                    and len(node.args) >= 2 \
                    and _conf_receiver(node.args[0]) \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                reads.append((node.args[1].value, node.lineno,
                              node.col_offset + 1))
        if reads:
            out["knob_reads"] = reads
    if ctx.endswith("distributed/backend.py"):
        propagated: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg and kw.arg.startswith("VEGA_TPU_"):
                        propagated.add(kw.arg)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and id(node) not in docstrings:
                m = re.match(r"(VEGA_TPU_[A-Z0-9_]*[A-Z0-9])(=|$)",
                             node.value)
                if m:
                    propagated.add(m.group(1))
        if propagated:
            out["propagation"] = sorted(propagated)

    # --- Configuration fields + fault knobs (resolution targets) -------
    if ctx.endswith("vega_tpu/env.py"):
        fields = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) \
                    and node.name == "Configuration":
                fields = [s.target.id for s in node.body
                          if isinstance(s, ast.AnnAssign)
                          and isinstance(s.target, ast.Name)]
        if fields:
            out["config_fields"] = fields
    if ctx.endswith("vega_tpu/faults.py"):
        knobs = sorted({
            node.value for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and id(node) not in docstrings
            and re.fullmatch(r"[A-Z][A-Z0-9_]*[A-Z0-9]", node.value)})
        if knobs:
            out["fault_knobs"] = knobs

    # --- every VEGA_TPU_* env literal (typo class) ----------------------
    if "VEGA_TPU_" in ctx.source:
        env_lits: List[tuple] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and id(node) not in docstrings \
                    and "VEGA_TPU_" in node.value:
                for m in _ENV_NAME_RE.finditer(node.value):
                    end = m.end()
                    if end < len(node.value) and node.value[end] == "_":
                        continue  # a prefix constant ("VEGA_TPU_FAULT_")
                    env_lits.append((m.group(0), node.lineno,
                                     node.col_offset + 1))
        if env_lits:
            out["env_literals"] = env_lits

    # --- event schema: classes, listener reads, emissions ---------------
    if ctx.endswith("scheduler/events.py"):
        classes: Dict[str, List[str]] = {}
        aggregated: List[str] = []
        for node in ctx.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            bases = {_last_name(b) for b in node.bases}
            if node.name == "Event" or "Event" in bases:
                classes[node.name] = [
                    s.target.id for s in node.body
                    if isinstance(s, ast.AnnAssign)
                    and isinstance(s.target, ast.Name)]
            if node.name == "MetricsListener":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and _last_name(sub.func) == "isinstance" \
                            and len(sub.args) == 2:
                        t = sub.args[1]
                        elts = t.elts if isinstance(t, ast.Tuple) else [t]
                        aggregated.extend(
                            n for n in (_last_name(e) for e in elts) if n)
        if classes:
            out["event_classes"] = classes
            out["event_aggregated"] = sorted(set(aggregated))
    if "on_event" in ctx.source:
        event_reads: List[tuple] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, _FUNC_DEFS) and node.name == "on_event":
                event_reads.extend(_event_reads_of(node))
        if event_reads:
            out["event_reads"] = event_reads
    # Emission sites resolve through the alias map, so a file with no
    # import landing on scheduler.events cannot emit — skip the walk.
    if any("scheduler.events" in v for v in ctx.aliases.values()):
        emits: List[tuple] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                qual = ctx.qualified(node.func)
                if qual and "scheduler.events." in qual:
                    emits.append((qual.rsplit(".", 1)[1], node.lineno,
                                  node.col_offset + 1))
        if emits:
            out["event_emits"] = emits

    return out or None


# ---------------------------------------------------------------------------
# VG009 — protocol conformance: every sent msg_type has a dispatch arm,
# every dispatch arm has a sender
# ---------------------------------------------------------------------------
# The message grammar lives in protocol.py prose; the send sites and the
# role handlers (worker._TaskHandler / shuffle_server._Handler /
# DriverService.dispatch, plus the client-side reply loops) are the code.
# PR 5's unknown-task_v2-marker desync was exactly a grammar/handler
# drift. A string sent via send_msg/encode_msg/request/_call with no
# `msg_type ==` (or reply_type/marker) arm anywhere in distributed/ is an
# unhandleable message; an arm no send site can reach is a dead handler.

@rule("VG009", "protocol message without dispatch arm / dead dispatch "
      "arm", project=True, extract=_contract_extract,
      extract_key="contracts")
def vg009(records: List[Tuple[str, dict]]) -> Iterator[Finding]:
    sends: Dict[str, tuple] = {}
    arms: Dict[str, tuple] = {}
    for display, data in records:
        for lit, line, col in data.get("sends", ()):
            sends.setdefault(lit, (display, line, col))
        for lit, line, col in data.get("arms", ()):
            arms.setdefault(lit, (display, line, col))
    if not sends or not arms:
        return  # no protocol surface in this tree
    for lit in sorted(set(sends) - set(arms)):
        display, line, col = sends[lit]
        yield Finding(
            "VG009", display, line, col,
            f"protocol message '{lit}' is sent but no dispatch arm "
            "compares msg_type/reply_type/marker against it — the "
            "receiver answers 'unknown' (or desyncs) at runtime; add the "
            "arm or fix the typo (grammar: distributed/protocol.py)")
    for lit in sorted(set(arms) - set(sends)):
        display, line, col = arms[lit]
        yield Finding(
            "VG009", display, line, col,
            f"dispatch arm for '{lit}' has no send site in the tree — "
            "dead handler: either wire up a sender or delete the arm "
            "(grammar: distributed/protocol.py)")


# ---------------------------------------------------------------------------
# VG010 — knob propagation: worker-side Configuration reads must reach
# spawned/ssh workers; every VEGA_TPU_* literal must resolve
# ---------------------------------------------------------------------------
# Context(conf=...) overrides only exist in the DRIVER process; a
# Configuration field read on the worker side (worker.py,
# shuffle_server.py, shuffle/) is silently stuck at its default in every
# spawned or ssh executor unless backend.py propagates the VEGA_TPU_*
# env var. And a typo'd env literal anywhere (tests included) configures
# nothing while looking like it does.

@rule("VG010", "worker-side Configuration read not propagated to "
      "workers / unresolvable VEGA_TPU_* env literal", project=True,
      extract=_contract_extract, extract_key="contracts")
def vg010(records: List[Tuple[str, dict]]) -> Iterator[Finding]:
    fields: Set[str] = set()
    fault_knobs: Set[str] = set()
    propagated: Set[str] = set()
    for _display, data in records:
        fields.update(data.get("config_fields", ()))
        fault_knobs.update(data.get("fault_knobs", ()))
        propagated.update(data.get("propagation", ()))
    if not fields:
        return  # no Configuration in this tree: nothing to resolve against
    if propagated:
        seen: Set[str] = set()
        for display, data in records:
            for field, line, col in data.get("knob_reads", ()):
                if field not in fields or field in seen:
                    continue
                seen.add(field)
                env_name = "VEGA_TPU_" + field.upper()
                if env_name not in propagated:
                    yield Finding(
                        "VG010", display, line, col,
                        f"worker-side read of Configuration.{field} but "
                        f"{env_name} is not in backend.py's worker "
                        "propagation list — driver-side overrides "
                        "silently never reach spawned/ssh executors "
                        "(add it to _worker_knobs)")
    for display, data in records:
        for name, line, col in data.get("env_literals", ()):
            if name in _VG010_ALLOWLIST:
                continue
            if name.startswith("VEGA_TPU_FAULT_"):
                if name[len("VEGA_TPU_FAULT_"):] in fault_knobs:
                    continue
            elif name[len("VEGA_TPU_"):].lower() in fields:
                continue
            yield Finding(
                "VG010", display, line, col,
                f"env literal '{name}' resolves to no Configuration "
                "field, faults.py knob, or known infrastructure knob — "
                "a typo here configures nothing while looking like it "
                "does")


# ---------------------------------------------------------------------------
# VG011 — event-schema conformance: listener reads exist on the event
# classes; every emitted event type is aggregated
# ---------------------------------------------------------------------------
# The bus delivers plain dataclasses; a misspelled attribute in a
# listener is an AttributeError swallowed by the bus's listener guard
# (log + continue), i.e. silently missing metrics. Reads inside an
# `isinstance(event, X)` branch are checked against X's own fields;
# un-narrowed reads pass if ANY event class has the field. An event type
# that is emitted but never aggregated by MetricsListener is invisible
# in every summary — aggregate it or pragma the emit site.

@rule("VG011", "listener reads a nonexistent event field / emitted "
      "event type not aggregated", project=True,
      extract=_contract_extract, extract_key="contracts")
def vg011(records: List[Tuple[str, dict]]) -> Iterator[Finding]:
    classes: Dict[str, Set[str]] = {}
    aggregated: Set[str] = set()
    for _display, data in records:
        for cls, fields in data.get("event_classes", {}).items():
            classes[cls] = set(fields)
        aggregated.update(data.get("event_aggregated", ()))
    if not classes:
        return  # no scheduler/events.py in this tree
    base = classes.get("Event", set())
    union: Set[str] = set(base)
    for fields in classes.values():
        union.update(fields)
    for display, data in records:
        for attr, line, col, narrow in data.get("event_reads", ()):
            if narrow:
                known = [c for c in narrow if c in classes]
                if not known:
                    continue  # narrowed to a non-bus class: out of scope
                ok = any(attr in classes[c] | base for c in known)
                scope = "/".join(known)
            else:
                ok = attr in union
                scope = "any event class"
            if not ok:
                yield Finding(
                    "VG011", display, line, col,
                    f"listener reads event.{attr}, which does not exist "
                    f"on {scope} (scheduler/events.py) — the bus guard "
                    "swallows the AttributeError, so this metric is "
                    "silently never recorded")
    emitted: Dict[str, tuple] = {}
    for display, data in records:
        for cls, line, col in data.get("event_emits", ()):
            if cls in classes and cls != "Event":
                emitted.setdefault(cls, (display, line, col))
    for cls in sorted(set(emitted) - aggregated):
        display, line, col = emitted[cls]
        yield Finding(
            "VG011", display, line, col,
            f"event type {cls} is emitted but MetricsListener never "
            "aggregates it — it is invisible in metrics_summary(); "
            "aggregate it or justify the emit site with a pragma")


# ---------------------------------------------------------------------------
# VG012 — unbounded blocking socket ops in distributed/ and shuffle/
# ---------------------------------------------------------------------------
# The PR 8 class: a hung shuffle owner gated a reduce task on the full
# 120s IO_TIMEOUT because one socket op ran without the push plan's
# deadline. On cross-process paths every raw recv/recv_into, connect
# without timeout, Future.result() without timeout, and settimeout(None)
# is a wait no deadline bounds — flag them all; the handful of
# deliberate unbounded waits carry justified pragmas.

_VG012_DIRS = (("vega_tpu", "distributed"), ("vega_tpu", "shuffle"),
               # Streaming receivers read sockets too (PR 16): a silent
               # peer must never wedge an ingest thread unboundedly.
               ("vega_tpu", "streaming"))


@rule("VG012", "unbounded blocking socket op on a cross-process path")
def vg012(ctx: FileCtx) -> Iterator[Finding]:
    if not any(ctx.in_dir(*d) for d in _VG012_DIRS):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _last_name(node.func)
        if name in ("recv", "recv_into") \
                and isinstance(node.func, ast.Attribute):
            yield Finding(
                "VG012", ctx.display, node.lineno, node.col_offset + 1,
                f"raw socket {name}() — nothing here bounds the wait; a "
                "hung peer parks this thread for the socket's full "
                "timeout (or forever). Route through the protocol "
                "helpers on a deadline-bearing socket, or justify with "
                "a pragma")
        elif name == "settimeout" and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is None:
            yield Finding(
                "VG012", ctx.display, node.lineno, node.col_offset + 1,
                "settimeout(None) removes the socket deadline — a hung "
                "peer now gates this path forever (the PR 8 hung-owner "
                "class); bound it or justify the unbounded wait with a "
                "pragma")
        elif name == "create_connection" and not _kw(node, "timeout") \
                and len(node.args) < 2:
            yield Finding(
                "VG012", ctx.display, node.lineno, node.col_offset + 1,
                "create_connection without timeout blocks the full OS "
                "connect timeout on a SYN-blackholed peer — pass "
                "timeout= (protocol.connect does)")
        elif name == "result" and not node.args \
                and not _kw(node, "timeout") \
                and isinstance(node.func, ast.Attribute):
            yield Finding(
                "VG012", ctx.display, node.lineno, node.col_offset + 1,
                "Future.result() without timeout on a cross-process "
                "path — a dead or wedged peer strands this thread; pass "
                "timeout= and handle the expiry")


# ---------------------------------------------------------------------------
# VG013 — frame planning must stay pure/lazy
# ---------------------------------------------------------------------------
# The frame subsystem's contract (same spirit as VG004's pure property
# reads): compiling a logical plan builds LINEAGE — it must never compute
# a partition, materialize a device block, or issue a device transfer.
# Every materializing entry point lives in vega_tpu/frame/api.py (the
# action surface); anywhere else in vega_tpu/frame/, a call to the
# materializing surface is a plan-build-time side effect — explain() or a
# mere DataFrame construction would launch device work at unpredictable
# times, and pushdown decisions would silently become value probing.

_VG013_BANNED_CALLS = {
    "collect", "collect_arrays", "collect_columns", "collect_grouped",
    "compute", "iterator", "block", "block_spec", "to_numpy", "host_get",
    "device_get", "device_put", "run_job", "submit_job",
    # The RDD actions: `if node.count() > t:` at plan-build time IS the
    # value-probing class this rule exists for.
    "count", "take", "reduce",
}
# counts_np only: it is unique to Block (a device counts fetch), while
# e.g. "num_rows" also names innocent pyarrow metadata — conservative by
# design (a crying-wolf rule gets pragma'd into silence).
_VG013_BANNED_ATTRS = {"counts_np"}


@rule("VG013", "materializing call at frame plan-build time")
def vg013(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu", "frame") or ctx.endswith("frame/api.py"):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = _last_name(node.func)
            if name in _VG013_BANNED_CALLS:
                yield Finding(
                    "VG013", ctx.display, node.lineno, node.col_offset + 1,
                    f"'{name}()' inside frame planning code — plan "
                    "compilation must stay pure/lazy (no partition "
                    "compute, no device block reads); materializing "
                    "actions belong in vega_tpu/frame/api.py "
                    "(docs/LINTING.md VG013)")
        elif isinstance(node, ast.Attribute) \
                and node.attr in _VG013_BANNED_ATTRS \
                and isinstance(node.ctx, ast.Load):
            yield Finding(
                "VG013", ctx.display, node.lineno, node.col_offset + 1,
                f"'.{node.attr}' read inside frame planning code — that "
                "is a device materialization/transfer; planning must stay "
                "pure (docs/LINTING.md VG013)")


# ---------------------------------------------------------------------------
# VG014 — exchange implementations must keep the exchange contract
# ---------------------------------------------------------------------------
# CLAUDE.md: "Every new exchange implementation keeps the (cols, count,
# overflow) contract and the n_shards==1 passthrough." With the planner
# (tpu/exchange_plan.py) choosing among exchange programs per launch, a
# new implementation that forgets either half would corrupt results only
# on the meshes/budgets that happen to select it — exactly the class a
# machine check must hold. An exchange ENTRY POINT is a public function
# in vega_tpu/tpu/ whose name ends in `_exchange` and takes the canonical
# call shape's `bucket` and `n_shards` arguments — what the exchange
# sites in dense_rdd.py actually invoke (passthrough_exchange — the
# shared gate target, which has neither by design — private `_`-prefixed
# helpers, and non-implementation functions like the planner's
# plan_exchange are exempt by that signature test). Each must (a)
# contain the single-shard gate: an `if n_shards == 1:` branch returning
# a call to passthrough_exchange or a delegation to another *_exchange
# function, and (b) return the triple at every return site — a literal
# 3-tuple or such a delegation.

_VG014_DIR = ("vega_tpu", "tpu")


def _vg014_is_exchange_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _last_name(node.func)
    return name is not None and name.endswith("_exchange")


def _vg014_gate_ok(fn: ast.AST) -> bool:
    """An `if n_shards == 1:` whose body returns an exchange call."""
    for node in _own_nodes(fn):
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if not (isinstance(t, ast.Compare) and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Eq)):
            continue
        sides = (t.left, t.comparators[0])
        names = [s.id for s in sides if isinstance(s, ast.Name)]
        ones = [s for s in sides
                if isinstance(s, ast.Constant) and s.value == 1]
        if "n_shards" not in names or not ones:
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.Return) \
                    and _vg014_is_exchange_call(stmt.value):
                return True
    return False


@rule("VG014", "exchange entry point violates the exchange contract")
def vg014(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir(*_VG014_DIR):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, _FUNC_DEFS):
            continue
        name = node.name
        if not name.endswith("_exchange") or name.startswith("_") \
                or name == "passthrough_exchange":
            continue
        args = node.args
        arg_names = {a.arg for a in args.posonlyargs + args.args
                     + args.kwonlyargs}
        if "n_shards" not in arg_names or "bucket" not in arg_names:
            continue  # not the exchange call shape (e.g. the planner)
        if not _vg014_gate_ok(node):
            yield Finding(
                "VG014", ctx.display, node.lineno, node.col_offset + 1,
                f"exchange entry point '{name}' is missing the "
                "single-shard gate (`if n_shards == 1: return "
                "passthrough_exchange(...)`)" " — every exchange "
                "implementation must keep the n_shards==1 passthrough "
                "(CLAUDE.md; docs/LINTING.md VG014)")
        for ret in _own_nodes(node):
            if not isinstance(ret, ast.Return):
                continue
            v = ret.value
            triple = isinstance(v, ast.Tuple) and len(v.elts) == 3
            if not triple and not _vg014_is_exchange_call(v):
                yield Finding(
                    "VG014", ctx.display, ret.lineno, ret.col_offset + 1,
                    f"return in exchange entry point '{name}' is neither "
                    "a (cols, count, overflow) 3-tuple nor a delegation "
                    "to another exchange — the exchange contract's "
                    "return shape (CLAUDE.md; docs/LINTING.md VG014)")


# ---------------------------------------------------------------------------
# VG015 — streaming state mutations flow through the commit API
# ---------------------------------------------------------------------------
# The exactly-once guarantee (PR 16) lives in ONE place:
# streaming/state.py's StateStore.apply_batch, which orders merge ->
# checkpoint -> atomic commit record and dedups replayed batch ids. Any
# other streaming code writing state fields, minting CommitLogs, or
# checkpointing state directly would fork that ordering — a crash between
# its write and the commit record silently violates exactly-once on
# exactly the replay path chaos tests exist to protect. (The socket-
# timeout half of this PR's lint work rides VG012, whose directory index
# now includes streaming/.)

_VG015_STATE_ATTRS = {"state", "_state", "last_committed_batch"}


@rule("VG015", "streaming state mutated outside the commit API")
def vg015(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu", "streaming") \
            or ctx.endswith("streaming/state.py"):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = _last_name(node.func)
            if name == "CommitLog":
                yield Finding(
                    "VG015", ctx.display, node.lineno, node.col_offset + 1,
                    "CommitLog minted outside streaming/state.py — commit "
                    "records must only be published by "
                    "StateStore.apply_batch, the one place that orders "
                    "merge -> checkpoint -> commit (docs/LINTING.md "
                    "VG015)")
            elif name == "write" and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "CheckpointRDD":
                yield Finding(
                    "VG015", ctx.display, node.lineno, node.col_offset + 1,
                    "CheckpointRDD.write of streaming state outside "
                    "streaming/state.py — state checkpoints must go "
                    "through StateStore.apply_batch so the atomic commit "
                    "record stays ordered after them (docs/LINTING.md "
                    "VG015)")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for target in targets:
                for sub in ast.walk(target):
                    if isinstance(sub, ast.Attribute) \
                            and sub.attr in _VG015_STATE_ATTRS:
                        yield Finding(
                            "VG015", ctx.display, sub.lineno,
                            sub.col_offset + 1,
                            f"direct write to streaming state "
                            f"('.{sub.attr}') outside streaming/state.py "
                            "— mutate state only via "
                            "StateStore.apply_batch (the exactly-once "
                            "commit API; docs/LINTING.md VG015)")


# ---------------------------------------------------------------------------
# VG016–VG019 — thread-role dataflow rules over the project call graph
# ---------------------------------------------------------------------------
# Implementations (extraction, graph build, role propagation, checks)
# live in vega_tpu/lint/callgraph.py — this block only registers them so
# importing `rules` populates the registry. VG016/VG019 are project
# rules sharing one cached per-file extraction (extract_key="callgraph",
# the VG009–VG012 contract-index shape); VG017/VG018 are self-contained
# per-file checks (capture and ship site, or acquire and release, are
# always in one function scope).

from vega_tpu.lint import callgraph as _cg  # noqa: E402


@rule("VG016", "blocking op reachable from a latency-critical role",
      doc="Blocking operations (device_get/host_get round trips, "
          "Future.result()/queue.get()/join()/subprocess waits without "
          "timeout, settimeout(None)) reachable — through the project "
          "call graph — from the latency-critical roles (dag-loop, "
          "arbiter, elastic, reaper). A stall there parks scheduling or "
          "liveness detection for every tenant. Spawning a thread ends "
          "the role: offloading to Thread(target=...) is the sanctioned "
          "escape hatch.",
      project=True, extract=_cg.extract_callgraph, extract_key="callgraph")
def vg016(records) -> Iterator[Finding]:
    yield from _cg.check_vg016(records)


@rule("VG017", "driver-only state captured into executor-shipped closure")
def vg017(ctx: FileCtx) -> Iterator[Finding]:
    """Closures passed to RDD ship methods (map/filter/reduce_by_key/...)
    must not capture driver-resident control-plane state — Context/
    scheduler/backend handles, Env, locks, sockets, jax device values.
    Shipping one fails at pickle time at best and runs against a stale
    stub at worst."""
    yield from _cg.check_vg017(ctx)


@rule("VG018", "socket/file acquired without release on every path")
def vg018(ctx: FileCtx) -> Iterator[Finding]:
    """In distributed//shuffle//streaming/, a socket or file bound to a
    local name must be released on EVERY path: `with`, contextlib.closing,
    or close in a finally. Returning/storing/passing the handle transfers
    ownership and is fine."""
    yield from _cg.check_vg018(ctx)


@rule("VG019", "driver-only function reachable from a confined role",
      doc="Functions in the driver-only seed set (Env mutation, context "
          "teardown, fleet mutation) or annotated "
          "`# vegalint: role[driver-only]` must not be reachable from "
          "the confined roles (worker-task, stream-receiver) in the "
          "project call graph — executor/ingest threads must never "
          "mutate driver state.",
      project=True, extract=_cg.extract_callgraph, extract_key="callgraph")
def vg019(records) -> Iterator[Finding]:
    yield from _cg.check_vg019(records)


def _vg020_is_object_dtype(node: ast.AST) -> bool:
    """True for the spellings that name the numpy object dtype: the
    `object` builtin, `np.object_`, and the 'O'/'object' dtype strings."""
    if isinstance(node, ast.Name) and node.id == "object":
        return True
    if isinstance(node, ast.Attribute) and node.attr in ("object_", "object"):
        return True
    if isinstance(node, ast.Constant) and node.value in ("O", "object"):
        return True
    return False


@rule("VG020", "object-dtype array created on a device-bound path")
def vg020(ctx: FileCtx) -> Iterator[Finding]:
    """Device-tier code (vega_tpu/tpu/) must never CREATE object-dtype
    numpy arrays: jax.device_put has no representation for them, so one
    reaching a shard program or device kernel dies with a raw TypeError
    mid-stage (block._check_dtype turns that into a crisp VegaError, but
    only at the block boundary — anything conjured past it is unguarded).
    Strings and Python objects cross the device boundary only as int32
    dictionary codes; tpu/dict_encoding.py is the one exempt file — it is
    the host-side encoder whose JOB is consuming such arrays to produce
    codes. Flags `dtype=object` / `dtype=np.object_` / `dtype="O"`
    keywords, the positional dtype of the common numpy constructors,
    `.astype(object)`-family calls, and `np.frompyfunc` (whose result is
    always an object array)."""
    if not ctx.in_dir("vega_tpu", "tpu"):
        return
    if ctx.endswith("tpu/dict_encoding.py"):
        return
    ctors = {"array", "asarray", "empty", "zeros", "ones", "full",
             "fromiter", "frombuffer"}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _last_name(node.func)
        if name == "frompyfunc":
            yield Finding(
                "VG020", ctx.display, node.lineno, node.col_offset + 1,
                "np.frompyfunc always returns an object-dtype array — "
                "object arrays have no device representation; encode "
                "through tpu/dict_encoding.py instead")
            continue
        hit = None
        for kw in node.keywords:
            if kw.arg == "dtype" and _vg020_is_object_dtype(kw.value):
                hit = kw.value
        if hit is None and name == "astype" and node.args \
                and _vg020_is_object_dtype(node.args[0]):
            hit = node.args[0]
        # positional dtype: arg index 1 for array/asarray/empty/zeros/
        # ones/fromiter/frombuffer, 2 for full (arg 1 is the fill value)
        pos = 2 if name == "full" else 1
        if hit is None and name in ctors and len(node.args) > pos \
                and _vg020_is_object_dtype(node.args[pos]):
            hit = node.args[pos]
        if hit is not None:
            yield Finding(
                "VG020", ctx.display, node.lineno, node.col_offset + 1,
                "object-dtype array created in device-tier code — object "
                "arrays have no device representation (jax.device_put "
                "raises); strings/objects cross the boundary only as "
                "int32 dictionary codes (tpu/dict_encoding.py)")
