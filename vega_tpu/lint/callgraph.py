"""vegalint v3: project-wide call graph + thread-role dataflow.

The engine is a multi-tenant service with a dozen distinct thread roles
(per-job DAG event loops, the task arbiter, the elastic controller, the
liveness reaper, fetch producer lanes, streaming receivers, the batch
driver, worker task threads), and the worst recent bug classes —
reaper-tick read races, event-loop stalls that skew straggler medians,
closures capturing driver-only state that only explode at pickle time —
are REACHABILITY bugs that per-file AST rules (VG001–VG015) structurally
cannot see. This module is the interprocedural layer:

* a per-file **extraction** (:func:`extract_callgraph`, cached in the
  engine's mtime-keyed ``FileRecord`` store exactly like the VG009–VG012
  contract index) reduces each file to def/call/closure/spawn facts;
* a global **combine** (:func:`build_graph`) joins them into a call
  graph — module functions, methods resolved through ``self`` and the
  single-inheritance class index, ``module.fn`` attribute chains through
  the import-alias map, and a guarded unique-name fallback for
  ``obj.method()`` receivers the AST cannot type;
* :func:`propagate_roles` seeds the graph with the DECLARED role map
  (:data:`ROLES`) and floods roles along call and callback edges.

Role propagation deliberately does NOT cross thread-spawn boundaries
(``threading.Thread(target=...)``, ``pool.submit``): offloading work to
a fresh thread is this codebase's idiom for *escaping* a latency-critical
role (the reaper hands a dead host's ssh kill to its own thread precisely
so liveness detection never blocks on it), so a spawn edge changes role
rather than inheriting it. Spawn targets get roles only via :data:`ROLES`.

Known limits (see docs/LINTING.md): dynamic dispatch through containers
of callables, `getattr` calls, and receivers typed only at runtime are
invisible; the unique-method-name fallback refuses common names
(``run``/``get``/``submit``/...) so one generic name cannot weld the
whole graph together. The runtime half (sync_witness role recording
under ``VEGA_TPU_DEBUG_SYNC=1``) cross-checks this static map against
observed thread identities.

Pure stdlib, same contract as engine.py: never imports jax or any
vega_tpu runtime module. sync_witness lazily imports THIS module for the
role table, so keep it import-light.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from vega_tpu.lint.engine import FileCtx, Finding

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

# --------------------------------------------------------------------------
# The declared role map — THE single source of truth, shared by the static
# rules (VG016/VG019) and the runtime witness (sync_witness.note_thread_role
# checks observed thread names against `thread_prefixes`).
# --------------------------------------------------------------------------
# critical: latency-sensitive control loop — a blocking op reachable from
#   it stalls scheduling/liveness for every tenant (VG016).
# confined: executor-/ingest-side — driver-only functions must not be
#   reachable from it (VG019).
ROLES: Dict[str, dict] = {
    "dag-loop": {
        "entries": (
            "vega_tpu.scheduler.jobserver.JobServer._drive",
            "vega_tpu.scheduler.dag.DAGScheduler._run_job_inner",
        ),
        "thread_prefixes": ("vega-job-",),
        "critical": True,
        "confined": False,
        "doc": "per-job DAG event loop (JobServer._drive thread)",
    },
    "arbiter": {
        # Not a thread of its own: arbiter methods run inline on job-loop
        # and task-callback threads, which is exactly why they must never
        # block (every pool's admission goes through them).
        "entries": (
            "vega_tpu.scheduler.jobserver.TaskArbiter.submit",
            "vega_tpu.scheduler.jobserver.TaskArbiter._pump",
            "vega_tpu.scheduler.jobserver.TaskArbiter._release",
        ),
        "thread_prefixes": (),
        "critical": True,
        "confined": False,
        "doc": "task arbiter (runs inline on job/callback threads)",
    },
    "elastic": {
        "entries": ("vega_tpu.scheduler.elastic.ElasticController._loop",),
        "thread_prefixes": ("elastic-controller",),
        "critical": True,
        "confined": False,
        "doc": "elastic controller tick",
    },
    "reaper": {
        "entries": (
            "vega_tpu.distributed.backend.DistributedBackend._reaper_loop",
        ),
        "thread_prefixes": ("executor-reaper",),
        "critical": True,
        "confined": False,
        "doc": "executor liveness reaper",
    },
    "fetch-producer": {
        "entries": (
            "vega_tpu.shuffle.fetcher.ShuffleFetcher._stream.produce",
        ),
        "thread_prefixes": ("shuffle-fetch",),
        "critical": False,
        "confined": False,
        "doc": "shuffle fetch producer lane",
    },
    "stream-receiver": {
        "entries": ("vega_tpu.streaming.source.Receiver._run",),
        "thread_prefixes": ("stream-recv-",),
        "critical": False,
        "confined": True,
        "doc": "streaming ingest receiver",
    },
    "batch-driver": {
        "entries": ("vega_tpu.streaming.context.StreamingContext._loop",),
        "thread_prefixes": ("stream-batches",),
        "critical": False,
        "confined": False,
        "doc": "micro-batch driver loop",
    },
    "worker-task": {
        # socketserver.ThreadingMixIn names handler threads generically,
        # so there is no name prefix to cross-check — the role is noted
        # explicitly at the top of _TaskHandler.handle.
        "entries": ("vega_tpu.distributed.worker._TaskHandler.handle",),
        "thread_prefixes": (),
        "critical": False,
        "confined": True,
        "doc": "executor task-serving thread",
    },
    "listener-bus": {
        "entries": (
            "vega_tpu.scheduler.events.LiveListenerBus._dispatch_loop",),
        "thread_prefixes": ("listener-bus",),
        "critical": False,
        "confined": False,
        "doc": "event listener dispatch loop",
    },
    "driver-api": {
        # The implicit default: user code on the main thread. Declared for
        # completeness/docs; nothing propagates from it.
        "entries": (),
        "thread_prefixes": (),
        "critical": False,
        "confined": False,
        "doc": "driver API (any un-noted thread, usually main)",
    },
}

CRITICAL_ROLES = tuple(r for r, s in ROLES.items() if s["critical"])
CONFINED_ROLES = tuple(r for r, s in ROLES.items() if s["confined"])

# Driver-only seed set for VG019 (beyond `# vegalint: role[driver-only]`
# annotations): Env mutation, driver mesh/context teardown, fleet
# mutation. `Env.reset` is also the worker BOOTSTRAP entry (main thread
# of the worker process) — that is fine; VG019 constrains reachability
# from the confined roles (task threads, receivers), not from main.
DRIVER_ONLY_SEEDS = (
    "vega_tpu.env.Env.reset",
    "vega_tpu.context.Context.stop",
    "vega_tpu.distributed.backend.DistributedBackend.add_executor",
    "vega_tpu.distributed.backend.DistributedBackend.remove_executor",
    "vega_tpu.scheduler.elastic.ElasticController.decommission",
)

_ROLE_COMMENT_RE = re.compile(r"#\s*vegalint:\s*role\[([a-z0-9_,\- ]+)\]")

# Unique-method-name fallback refuses these: one generic name must not
# weld unrelated subsystems into a single role blob.
_COMMON_METHOD_NAMES = frozenset({
    "run", "start", "stop", "get", "put", "set", "close", "submit",
    "send", "recv", "join", "wait", "result", "acquire", "release",
    "append", "add", "pop", "clear", "update", "read", "write", "open",
    "items", "keys", "values", "copy", "flush", "next", "handle",
    "count", "reduce", "collect", "map", "filter", "post", "emit",
    "name", "main", "connect", "shutdown", "cancel", "done", "fetch",
    "compute", "iterator", "serve", "dispatch", "encode", "decode",
    "load", "dump", "dumps", "loads", "register", "unregister",
})

# RDD-surface methods whose function argument is pickled and shipped to
# executors (VG017).
_SHIP_METHODS = frozenset({
    "map", "filter", "flat_map", "map_partitions",
    "map_partitions_with_index", "map_values", "flat_map_values",
    "key_by", "foreach", "foreach_partition", "reduce_by_key",
    "combine_by_key", "aggregate", "aggregate_by_key", "fold",
    "fold_by_key", "sort_by", "group_by", "starmap", "tree_aggregate",
})

# Classes whose instances are driver-resident control-plane state: a
# closure capturing `self` (or a binding constructed from them) must not
# ship to executors.
_DRIVER_ONLY_CLASSES = frozenset({
    "Context", "StreamingContext", "DAGScheduler", "JobServer",
    "TaskArbiter", "ElasticController", "DistributedBackend",
    "DriverService", "Env", "LiveListenerBus",
})

# Attribute names whose read is a driver control-plane handle.
_DRIVER_HANDLE_ATTRS = frozenset({
    "context", "scheduler", "_scheduler", "dag_scheduler", "backend",
    "_backend", "job_server", "_job_server",
})


def _last_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


def _own_nodes(root: ast.AST) -> Iterator[ast.AST]:
    """Descendants excluding nested def/lambda subtrees (they run later,
    possibly on a different thread)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------
# Blocking-operation classifier (VG016). Raw socket recv() is deliberately
# NOT listed: recv boundedness is socket-state-dependent and VG012 already
# polices raw recvs lexically in the cross-process dirs; here we flag the
# shapes that are unbounded regardless of state.
# --------------------------------------------------------------------------
def _blocking_site(call: ast.Call, ctx: FileCtx) -> Optional[str]:
    name = _last_name(call.func)
    if name in ("device_get", "host_get"):
        # Only the LEAF transfer: a call resolving into the project
        # (mesh.host_get, compat wrappers) is followed by the graph, and
        # the jax.device_get inside it is flagged once, where it lives —
        # flagging every transitive caller would bury the signal.
        qual = ctx.qualified(call.func) or ""
        if not qual.startswith("vega_tpu."):
            return f"{name}() — a driver<->device round trip"
    if name == "settimeout" and len(call.args) == 1 \
            and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is None:
        return "settimeout(None) — removes the socket deadline"
    if name == "create_connection" and not _kw(call, "timeout") \
            and len(call.args) < 2:
        return "create_connection without timeout"
    if name == "result" and not call.args and not _kw(call, "timeout") \
            and isinstance(call.func, ast.Attribute):
        return "Future.result() without timeout"
    if name == "get" and isinstance(call.func, ast.Attribute) \
            and not call.args and not _kw(call, "timeout"):
        recv = call.func.value
        rname = (recv.attr if isinstance(recv, ast.Attribute)
                 else recv.id if isinstance(recv, ast.Name) else "") or ""
        if "queue" in rname.lower() or rname in ("q", "inq", "outq"):
            return "queue get() without timeout"
    if name in ("wait", "communicate") and not call.args \
            and not _kw(call, "timeout") \
            and isinstance(call.func, ast.Attribute):
        return f"{name}() without timeout"
    if name == "join" and not call.args and not _kw(call, "timeout") \
            and isinstance(call.func, ast.Attribute) \
            and not isinstance(call.func.value, ast.Constant):
        # `t.join()` (thread) — `"sep".join(parts)` always has an arg.
        return "join() without timeout"
    qual = ctx.qualified(call.func) or ""
    if qual.startswith("subprocess.") and qual.split(".")[-1] in (
            "run", "call", "check_call", "check_output") \
            and not _kw(call, "timeout"):
        return f"{qual}() without timeout"
    return None


# --------------------------------------------------------------------------
# Per-file extraction
# --------------------------------------------------------------------------
def _role_comment_lines(ctx: FileCtx) -> Dict[int, List[str]]:
    out: Dict[int, List[str]] = {}
    for i, line in enumerate(ctx.lines, start=1):
        m = _ROLE_COMMENT_RE.search(line)
        if m:
            out[i] = [s.strip() for s in m.group(1).split(",") if s.strip()]
    return out


def _thread_target_args(call: ast.Call) -> List[ast.AST]:
    """The callable operands of a thread/pool spawn call — role
    propagation must NOT follow them."""
    name = _last_name(call.func)
    out: List[ast.AST] = []
    if name == "Thread":
        for k in call.keywords:
            if k.arg == "target":
                out.append(k.value)
    elif name in ("submit", "apply_async", "start_new_thread",
                  "run_in_executor", "defer"):
        if call.args:
            out.append(call.args[0])
    return out


def _ref_descs(node: ast.AST, ctx: FileCtx, file_funcs: Set[str],
               cls_methods: Set[str]) -> List[tuple]:
    """Descriptors for a bare function reference (callback argument)."""
    if isinstance(node, ast.Name):
        alias = ctx.aliases.get(node.id)
        if alias and alias.startswith("vega_tpu."):
            return [("qual", alias)]
        if node.id in file_funcs:
            return [("name", node.id)]
    elif isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name):
        if node.value.id == "self" and node.attr in cls_methods:
            return [("self", node.attr)]
        qual = ctx.qualified(node)
        if qual and qual.startswith("vega_tpu."):
            return [("qual", qual)]
    return []


def extract_callgraph(ctx: FileCtx) -> Optional[dict]:
    """Per-file facts for the project call graph (cached by the engine)."""
    if not ctx.in_dir("vega_tpu"):
        return None
    role_lines = _role_comment_lines(ctx)

    funcs: Dict[str, dict] = {}
    classes: Dict[str, dict] = {}
    # Pre-pass: every function name defined anywhere in the file (for
    # callback-reference filtering) and class -> method names.
    file_funcs: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_DEFS):
            file_funcs.add(node.name)

    def scan_function(fn: ast.AST, qual: str, cls: Optional[str]) -> None:
        cls_methods = set(classes.get(cls, {}).get("methods", ())) \
            if cls else set()
        roles = []
        for ln in (fn.lineno, fn.lineno - 1):
            roles.extend(role_lines.get(ln, ()))
        calls: List[tuple] = []
        refs: List[tuple] = []
        spawns: List[tuple] = []
        blocking: List[tuple] = []
        skip_ref_ids: Set[int] = set()
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            for tgt in _thread_target_args(node):
                skip_ref_ids.add(id(tgt))
                spawns.extend(_ref_descs(tgt, ctx, file_funcs,
                                         cls_methods))
            b = _blocking_site(node, ctx)
            if b:
                blocking.append((b, node.lineno, node.col_offset + 1))
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self":
                calls.append(("self", f.attr))
            elif isinstance(f, ast.Name):
                alias = ctx.aliases.get(f.id)
                if alias and alias.startswith("vega_tpu."):
                    calls.append(("qual", alias))
                else:
                    calls.append(("name", f.id))
            elif isinstance(f, ast.Attribute):
                qualn = ctx.qualified(f)
                if qualn and qualn.startswith("vega_tpu."):
                    calls.append(("qual", qualn))
                else:
                    calls.append(("attr", f.attr))
            for a in list(node.args) + [k.value for k in node.keywords]:
                if id(a) in skip_ref_ids:
                    continue
                refs.extend(_ref_descs(a, ctx, file_funcs, cls_methods))
        funcs[qual] = {
            "line": fn.lineno,
            "cls": cls,
            "roles": roles,
            "calls": sorted(set(calls)),
            "refs": sorted(set(refs)),
            "spawns": sorted(set(spawns)),
            "blocking": blocking,
        }
        for sub in ast.iter_child_nodes(fn):
            walk_scope(sub, qual, cls)

    def walk_scope(node: ast.AST, prefix: str,
                   cls: Optional[str]) -> None:
        if isinstance(node, _FUNC_DEFS):
            qual = f"{prefix}.{node.name}" if prefix else node.name
            scan_function(node, qual, cls)
        elif isinstance(node, ast.ClassDef):
            methods = {s.name for s in node.body
                       if isinstance(s, _FUNC_DEFS)}
            classes[node.name] = {
                "methods": sorted(methods),
                "bases": [b for b in (_last_name(x) for x in node.bases)
                          if b],
            }
            for sub in node.body:
                walk_scope(sub, node.name, node.name)
        else:
            for sub in ast.iter_child_nodes(node):
                walk_scope(sub, prefix, cls)

    for node in ctx.tree.body:
        walk_scope(node, "", None)

    if not funcs:
        return None
    return {"module": ctx.module, "funcs": funcs, "classes": classes}


# --------------------------------------------------------------------------
# Combine: graph build + role propagation
# --------------------------------------------------------------------------
class Graph:
    def __init__(self) -> None:
        self.defs: Dict[str, dict] = {}  # full qual -> info (+file)
        self.edges: Dict[str, Set[str]] = {}  # resolved call/ref edges
        self.classes: Dict[str, List[Tuple[str, dict]]] = {}  # name->defs
        self.subclasses: Dict[str, Set[str]] = {}  # name -> subclass names
        self.by_method: Dict[str, List[str]] = {}  # bare name -> quals

    def add_edge(self, src: str, dst: str) -> None:
        self.edges.setdefault(src, set()).add(dst)


def _method_quals(g: Graph, cls: str, attr: str,
                  seen: Optional[Set[str]] = None) -> List[str]:
    """Resolve a method on class `cls` (by name): own def, else base
    defs; plus overrides in every transitive subclass (role propagation
    must reach the override that actually runs)."""
    seen = seen if seen is not None else set()
    if cls in seen:
        return []
    seen.add(cls)
    out: List[str] = []
    for module, info in g.classes.get(cls, ()):
        if attr in info.get("methods", ()):
            out.append(f"{module}.{cls}.{attr}")
        else:
            for base in info.get("bases", ()):
                out.extend(_method_quals(g, base, attr, seen))
    for sub in g.subclasses.get(cls, ()):
        if sub in seen:
            continue
        for module, info in g.classes.get(sub, ()):
            if attr in info.get("methods", ()):
                out.append(f"{module}.{sub}.{attr}")
        out.extend(q for q in _method_quals(g, sub, attr, seen)
                   if q not in out)
    return out


def build_graph(records: List[Tuple[str, dict]]) -> Graph:
    g = Graph()
    for display, data in records:
        module = data["module"]
        for qual, info in data["funcs"].items():
            full = f"{module}.{qual}"
            g.defs[full] = dict(info, file=display, module=module)
            g.by_method.setdefault(qual.rsplit(".", 1)[-1],
                                   []).append(full)
        for cls, cinfo in data.get("classes", {}).items():
            g.classes.setdefault(cls, []).append((module, cinfo))
            for base in cinfo.get("bases", ()):
                g.subclasses.setdefault(base, set()).add(cls)

    for full, info in g.defs.items():
        module = info["module"]
        cls = info["cls"]
        for desc in list(info["calls"]) + list(info["refs"]):
            kind, name = desc[0], desc[1]
            if kind == "self" and cls:
                for q in _method_quals(g, cls, name):
                    g.add_edge(full, q)
            elif kind == "name":
                # Nearest enclosing scope first: nested def, then outer
                # scopes, then module level.
                parts = full.split(".")
                for depth in range(len(parts), 0, -1):
                    cand = ".".join(parts[:depth] + [name])
                    if cand in g.defs:
                        g.add_edge(full, cand)
                        break
            elif kind == "qual":
                if name in g.defs:
                    g.add_edge(full, name)
                else:
                    # `Cls.meth` via an alias: resolve through the class
                    # index (covers subclass overrides too).
                    head, _, attr = name.rpartition(".")
                    cname = head.rsplit(".", 1)[-1] if head else ""
                    if cname and cname in g.classes:
                        for q in _method_quals(g, cname, attr):
                            g.add_edge(full, q)
            elif kind == "attr":
                if name in _COMMON_METHOD_NAMES or name.startswith("__"):
                    continue
                cands = g.by_method.get(name, ())
                if len(cands) == 1:
                    g.add_edge(full, cands[0])
    return g


def propagate_roles(g: Graph) -> Tuple[Dict[str, Set[str]],
                                       Dict[Tuple[str, str], str]]:
    """Flood roles from ROLES entries along resolved edges. Returns
    (roles-per-qual, parent map keyed by (qual, role) for witness
    paths)."""
    roles: Dict[str, Set[str]] = {}
    parent: Dict[Tuple[str, str], str] = {}
    frontier: List[Tuple[str, str]] = []
    for role, spec in ROLES.items():
        for entry in spec["entries"]:
            if entry in g.defs:
                roles.setdefault(entry, set()).add(role)
                frontier.append((entry, role))
    while frontier:
        qual, role = frontier.pop()
        for nxt in g.edges.get(qual, ()):
            have = roles.setdefault(nxt, set())
            if role in have:
                continue
            have.add(role)
            parent[(nxt, role)] = qual
            frontier.append((nxt, role))
    return roles, parent


def witness_path(parent: Dict[Tuple[str, str], str], qual: str,
                 role: str) -> List[str]:
    """Entry -> ... -> qual call chain that carried `role` to `qual`."""
    path = [qual]
    seen = {qual}
    while (path[-1], role) in parent:
        prev = parent[(path[-1], role)]
        if prev in seen:
            break
        path.append(prev)
        seen.add(prev)
    return list(reversed(path))


def _short(qual: str) -> str:
    parts = qual.split(".")
    return ".".join(parts[-2:]) if len(parts) > 2 else qual


def _render_path(parent, qual: str, role: str) -> str:
    return " -> ".join(_short(q) for q in witness_path(parent, qual, role))


# --------------------------------------------------------------------------
# VG016 — blocking operations reachable from latency-critical roles
# --------------------------------------------------------------------------
def check_vg016(records: List[Tuple[str, dict]]) -> Iterator[Finding]:
    g = build_graph(records)
    roles, parent = propagate_roles(g)
    for qual, info in sorted(g.defs.items()):
        crit = sorted(r for r in roles.get(qual, ()) if r in CRITICAL_ROLES)
        if not crit or not info["blocking"]:
            continue
        role = crit[0]
        for desc, line, col in info["blocking"]:
            yield Finding(
                "VG016", info["file"], line, col,
                f"{desc}, reachable from latency-critical role "
                f"'{role}' (path: {_render_path(parent, qual, role)}) — "
                "a stall here parks scheduling/liveness for every "
                "tenant; bound the wait or offload to a spawned thread "
                "(spawn boundaries end the role)")


# --------------------------------------------------------------------------
# VG019 — role confinement: driver-only functions unreachable from
# worker/receiver roles
# --------------------------------------------------------------------------
def check_vg019(records: List[Tuple[str, dict]]) -> Iterator[Finding]:
    g = build_graph(records)
    roles, parent = propagate_roles(g)
    driver_only: Dict[str, str] = {}
    for qual in DRIVER_ONLY_SEEDS:
        if qual in g.defs:
            driver_only[qual] = "seed set"
    for qual, info in g.defs.items():
        if "driver-only" in info.get("roles", ()):
            driver_only[qual] = "role[driver-only] annotation"
    for qual, why in sorted(driver_only.items()):
        bad = sorted(r for r in roles.get(qual, ())
                     if r in CONFINED_ROLES)
        for role in bad:
            info = g.defs[qual]
            yield Finding(
                "VG019", info["file"], info["line"], 1,
                f"driver-only function '{_short(qual)}' ({why}) is "
                f"reachable from confined role '{role}' (path: "
                f"{_render_path(parent, qual, role)}) — executor/"
                "receiver threads must never mutate driver state")


# --------------------------------------------------------------------------
# VG017 — driver-only state captured into executor-shipped closures
# (self-contained per file: the capture, its binding, and the ship site
# are all in one function scope)
# --------------------------------------------------------------------------
def _driver_only_binding(expr: ast.AST, ctx: FileCtx) -> Optional[str]:
    """Why the bound value is driver-only, or None."""
    if isinstance(expr, ast.Call):
        name = _last_name(expr.func)
        qual = ctx.qualified(expr.func) or ""
        if name in _DRIVER_ONLY_CLASSES:
            return f"a {name} instance"
        if name in ("Lock", "RLock", "Condition", "named_lock"):
            return "a lock"
        if qual in ("socket.socket", "socket.create_connection") \
                or qual.endswith("protocol.connect"):
            return "a socket"
        if name == "get" and isinstance(expr.func, ast.Attribute) \
                and _last_name(expr.func.value) == "Env":
            return "the Env singleton"
        if qual.startswith(("jax.", "jnp.")):
            return "a jax device value"
    elif isinstance(expr, ast.Attribute):
        if expr.attr in _DRIVER_HANDLE_ATTRS:
            return f"a driver handle (.{expr.attr})"
    return None


def _closure_free_loads(fn: ast.AST) -> Set[str]:
    """Names loaded inside a closure (lambda or def, including default
    arg expressions) that the closure itself does not bind."""
    bound: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        bound.add(a.arg)
    loads: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.add(node.id)
            else:
                bound.add(node.id)
    return loads - bound


def check_vg017(ctx: FileCtx) -> Iterator[Finding]:
    if not ctx.in_dir("vega_tpu"):
        return
    for outer in ast.walk(ctx.tree):
        if not isinstance(outer, _FUNC_DEFS):
            continue
        # Enclosing-scope facts: local bindings and nested defs.
        bindings: Dict[str, ast.AST] = {}
        nested: Dict[str, ast.AST] = {}
        enclosing_cls: Optional[str] = None
        for node in _own_nodes(outer):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                bindings[node.targets[0].id] = node.value
        for node in ast.iter_child_nodes(outer):
            if isinstance(node, _FUNC_DEFS):
                nested[node.name] = node
        for node in _own_nodes(outer):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SHIP_METHODS):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                closure: Optional[ast.AST] = None
                if isinstance(arg, ast.Lambda):
                    closure = arg
                elif isinstance(arg, ast.Name) and arg.id in nested:
                    closure = nested[arg.id]
                if closure is None:
                    continue
                for var in sorted(_closure_free_loads(closure)):
                    why = None
                    if var in bindings:
                        why = _driver_only_binding(bindings[var], ctx)
                    if why is None:
                        continue
                    yield Finding(
                        "VG017", ctx.display, node.lineno,
                        node.col_offset + 1,
                        f"closure passed to .{node.func.attr}() captures "
                        f"'{var}', bound to {why} — driver-only state "
                        "shipped to executors fails at pickle time at "
                        "best, runs against a stub at worst; pass plain "
                        "data in, or compute on the driver first")


# --------------------------------------------------------------------------
# VG018 — leaked sockets/files in distributed/, shuffle/, streaming/
# --------------------------------------------------------------------------
_VG018_DIRS = (("vega_tpu", "distributed"), ("vega_tpu", "shuffle"),
               ("vega_tpu", "streaming"))


def _acquisition_desc(call: ast.Call, ctx: FileCtx) -> Optional[str]:
    qual = ctx.qualified(call.func) or ""
    name = _last_name(call.func)
    if qual in ("socket.socket", "socket.create_connection"):
        return f"{name}()"
    if name == "connect" and qual.endswith("protocol.connect"):
        return "protocol.connect()"
    if isinstance(call.func, ast.Name) and call.func.id == "open" \
            and "open" not in ctx.aliases:
        return "open()"
    return None


def _scan_vg018_fn(fn: ast.AST, ctx: FileCtx) -> Iterator[Finding]:
    acquired: List[Tuple[str, str, int, int]] = []  # (var, desc, ln, col)
    for node in _own_nodes(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.value, ast.Call):
            desc = _acquisition_desc(node.value, ctx)
            if desc is None:
                continue
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                acquired.append((tgt.id, desc, node.lineno,
                                 node.col_offset + 1))
    if not acquired:
        return
    # Names released inside a `finally:` (any Try's finalbody), handed
    # to contextlib.closing, or used as a `with` context manager.
    released: Set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in ("close", "shutdown") \
                            and isinstance(sub.func.value, ast.Name):
                        released.add(sub.func.value.id)
        elif isinstance(node, ast.Call) \
                and _last_name(node.func) == "closing":
            for a in node.args:
                if isinstance(a, ast.Name):
                    released.add(a.id)
        elif isinstance(node, ast.With):
            for item in node.items:
                if isinstance(item.context_expr, ast.Name):
                    released.add(item.context_expr.id)
    # Names that escape the function (ownership transfer): returned,
    # yielded, stored into an attribute/container, or passed to a call.
    escaped: Set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    escaped.add(sub.id)
        elif isinstance(node, ast.Call):
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Name):
                    escaped.add(a.id)
        elif isinstance(node, ast.Assign):
            tgt = node.targets[0]
            if isinstance(tgt, (ast.Attribute, ast.Subscript, ast.Tuple)) \
                    and isinstance(node.value, ast.Name):
                escaped.add(node.value.id)
    for var, desc, line, col in acquired:
        if var in released or var in escaped:
            continue
        yield Finding(
            "VG018", ctx.display, line, col,
            f"{desc} assigned to '{var}' with no `with`/try-finally "
            "release on this path — an exception between acquire and "
            "close leaks the handle (and on this 1-core sandbox, a "
            "leaked socket holds its peer's accept slot); wrap in "
            "`with closing(...)` or close in a finally")


def check_vg018(ctx: FileCtx) -> Iterator[Finding]:
    if not any(ctx.in_dir(*d) for d in _VG018_DIRS):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_DEFS):
            yield from _scan_vg018_fn(node, ctx)


# --------------------------------------------------------------------------
# --explain-role support
# --------------------------------------------------------------------------
def explain(records: List[Tuple[str, dict]], needle: str) -> List[dict]:
    """Functions whose full qual ends with `needle`, each with its
    propagated roles and one witness call path per role."""
    g = build_graph(records)
    roles, parent = propagate_roles(g)
    out = []
    for qual in sorted(g.defs):
        if qual == needle or qual.endswith("." + needle):
            out.append({
                "function": qual,
                "file": g.defs[qual]["file"],
                "line": g.defs[qual]["line"],
                "roles": {r: witness_path(parent, qual, r)
                          for r in sorted(roles.get(qual, ()))},
            })
    return out
