"""vegalint: the project's invariant linter.

The invariants that keep vega_tpu correct (compat-shimmed jax access, no
device probing on import paths, pure placement-property reads, serialized
device reads under cache locks, ...) used to live only in CLAUDE.md prose;
two of them caused real incidents before this package existed (the
seed-suite XLA:CPU deadlock, the jax-0.4 dense-tier wipeout). vegalint
turns each written invariant into a machine-checked rule:

    python -m vega_tpu.lint vega_tpu tests bench.py

Rule catalog: docs/LINTING.md (or ``python -m vega_tpu.lint --list-rules``).
Runtime companion: ``vega_tpu.lint.sync_witness`` — under
``VEGA_TPU_DEBUG_SYNC=1`` the named locks record their acquisition order
per thread and raise on inversion, so VG003's static lock-order graph is
double-checked dynamically by every tier-1 run that sets the flag.

This package must stay importable without jax (it is imported at lock
construction time by core modules via sync_witness) and without the rest
of vega_tpu (the CLI lints a tree it never imports).
"""

from vega_tpu.lint.engine import Finding, LintResult, all_rules, run_lint

__all__ = ["Finding", "LintResult", "all_rules", "run_lint"]
