"""vegalint core: file model, rule registry, pragma handling, reporters.

Pure stdlib (ast + re) — the linter must run in well under ten seconds on
the 1-core sandbox and must not import jax or any vega_tpu runtime module
(it lints source trees it never executes).

Rule protocol
-------------
A rule is registered with :func:`rule` and receives either one
:class:`FileCtx` (per-file rules) or the whole list (``project=True`` —
needed by the lock-order analysis, whose acquisition graph spans modules)
and yields :class:`Finding` objects.

Pragmas
-------
A finding on line N is suppressed when line N — or a standalone comment
line directly above it — carries::

    # vegalint: ignore[VG003] — one-line justification

The justification is MANDATORY: a pragma without one is itself a finding
(VG000, not suppressible), which is how the acceptance criterion "every
ignore carries a justification" is machine-enforced rather than reviewed.
``ignore[*]`` suppresses every rule on that line (same justification duty).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Tuple

PRAGMA_RE = re.compile(
    r"#\s*vegalint:\s*ignore\[([^\]]*)\]\s*(.*)$"
)
# Leading em-dash / dash / colon before the justification text.
_JUSTIFY_STRIP = " \t—–:-"


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # as given on the command line (relative where possible)
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if self.justification is None:
            d.pop("justification")
        return d

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.justification if self.suppressed \
            else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}{tag}"


@dataclasses.dataclass
class Rule:
    id: str
    title: str
    doc: str  # rationale + example, surfaced by --list-rules and the docs
    check: Callable
    project: bool = False  # True: check(list[FileCtx]); else check(FileCtx)


_RULES: Dict[str, Rule] = {}


def rule(rule_id: str, title: str, doc: str = "", project: bool = False):
    def register(fn):
        _RULES[rule_id] = Rule(rule_id, title, doc or (fn.__doc__ or ""),
                               fn, project)
        return fn

    return register


def all_rules() -> Dict[str, Rule]:
    # Importing the rules module populates the registry on first use.
    from vega_tpu.lint import rules  # noqa: F401

    return dict(_RULES)


class FileCtx:
    """One parsed source file plus the import-alias map rules share."""

    def __init__(self, path: str, display: str, source: str):
        self.path = path
        self.display = display  # normalized, '/'-separated, for reporting
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = _collect_aliases(self.tree)
        # pragma line -> (set of rule ids or {'*'}, justification, col).
        # Pragmas are read from real COMMENT tokens, so a docstring that
        # *mentions* the syntax (this engine's own, say) is not a pragma.
        self.pragmas: Dict[int, Tuple[set, str, int]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if m:
                    ids = {s.strip() for s in m.group(1).split(",")
                           if s.strip()}
                    just = m.group(2).strip(_JUSTIFY_STRIP).strip()
                    self.pragmas[tok.start[0]] = (
                        ids, just, tok.start[1] + m.start() + 1)
        except tokenize.TokenError:
            pass  # the ast parse already succeeded; just no pragmas

    # ---------------------------------------------------------- path scoping
    def in_dir(self, *parts: str) -> bool:
        """True when the file lives under a directory path containing the
        given '/'-joined fragment (e.g. in_dir('vega_tpu', 'tpu'))."""
        return "/" + "/".join(parts) + "/" in "/" + self.display

    def endswith(self, suffix: str) -> bool:
        return self.display.endswith(suffix)

    @property
    def module(self) -> str:
        """Dotted module name anchored at the last 'vega_tpu' path segment
        (lock keys and messages use it); top-level scripts use the stem."""
        parts = self.display.split("/")
        anchors = [i for i, p in enumerate(parts[:-1]) if p == "vega_tpu"]
        if anchors:
            parts = parts[anchors[-1]:]
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    # ----------------------------------------------------------- ast helpers
    def qualified(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases
        expanded: `jnp.nonzero` -> 'jax.numpy.nonzero' after
        `import jax.numpy as jnp`."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        return ".".join([base] + list(reversed(parts)))


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # unsuppressed, reported, gate exit status
    suppressed: List[Finding]
    files: int
    errors: List[str]  # unparseable files etc.

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def to_dict(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "ok": self.ok,
            "files": self.files,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "errors": self.errors,
            "by_rule": counts,
        }


def discover(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def run_lint(paths: Iterable[str],
             select: Optional[Iterable[str]] = None) -> LintResult:
    rules = all_rules()
    if select:
        keep = set(select)
        unknown = keep - set(rules)
        if unknown:
            # A typo'd --select silently checking nothing would report the
            # invariant gate green — fail loudly instead.
            raise ValueError(f"unknown rule id(s) in select: "
                             f"{sorted(unknown)}; known: {sorted(rules)}")
        rules = {rid: r for rid, r in rules.items() if rid in keep}
    ctxs: List[FileCtx] = []
    errors: List[str] = []
    paths = list(paths)
    for p in paths:
        # Same rationale: a mistyped path must not make the gate pass
        # vacuously.
        if not os.path.exists(p):
            errors.append(f"{p}: path does not exist")
        elif not os.path.isdir(p) and not p.endswith(".py"):
            errors.append(f"{p}: not a directory or .py file")
    files = discover(paths)
    for path in files:
        display = os.path.normpath(path).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            ctxs.append(FileCtx(path, display, source))
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            errors.append(f"{display}: {type(exc).__name__}: {exc}")

    raw: List[Finding] = []
    for r in rules.values():
        if r.project:
            raw.extend(r.check(ctxs))
        else:
            for ctx in ctxs:
                raw.extend(r.check(ctx))

    by_display = {c.display: c for c in ctxs}
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    used_pragmas: Dict[Tuple[str, int], bool] = {}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        ctx = by_display.get(f.path)
        hit = _pragma_for(ctx, f) if ctx is not None else None
        if hit is not None and f.rule != "VG000":
            line, (_ids, just, _col) = hit
            used_pragmas[(f.path, line)] = True
            f.suppressed = True
            f.justification = just or None
            suppressed.append(f)
        else:
            findings.append(f)

    # Pragma hygiene (VG000): a pragma must carry a justification; a pragma
    # that names no known rule, or suppresses nothing, is dead weight —
    # either the invariant code was fixed (delete the pragma) or the rule
    # drifted (fix the rule). Not themselves suppressible.
    known = set(all_rules()) | {"*"}
    for ctx in ctxs:
        for line, (ids, just, col) in sorted(ctx.pragmas.items()):
            if not just:
                findings.append(Finding(
                    "VG000", ctx.display, line, col,
                    "pragma without justification — write "
                    "'# vegalint: ignore[RULE] — why this is safe'"))
            unknown = ids - known
            if unknown:
                findings.append(Finding(
                    "VG000", ctx.display, line, col,
                    f"pragma names unknown rule(s) {sorted(unknown)}"))
            elif select is None \
                    and not used_pragmas.get((ctx.display, line)):
                findings.append(Finding(
                    "VG000", ctx.display, line, col,
                    f"pragma suppresses nothing (rules {sorted(ids)} did "
                    "not fire here) — delete it or re-anchor it"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings, suppressed, len(ctxs), errors)


def _pragma_for(ctx: FileCtx, f: Finding):
    """Pragma applying to finding `f`: same line, or a standalone comment
    line directly above."""
    for line in (f.line, f.line - 1):
        hit = ctx.pragmas.get(line)
        if hit is None:
            continue
        if line == f.line - 1:
            text = ctx.lines[line - 1].lstrip() if line >= 1 else ""
            if not text.startswith("#"):
                continue  # trailing pragma on the previous code line
        ids = hit[0]
        if f.rule in ids or "*" in ids:
            return line, hit
    return None


# ------------------------------------------------------------------ reporters
def render_text(result: LintResult) -> str:
    lines = [f.render() for f in result.findings]
    lines.extend(f"error: {e}" for e in result.errors)
    lines.append(
        f"vegalint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, {result.files} file(s)"
    )
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_dict(), indent=1, sort_keys=True)
