"""vegalint core: file model, rule registry, pragma handling, cache,
reporters.

Pure stdlib (ast + re + pickle) — the linter must run in well under ten
seconds on the 1-core sandbox and must not import jax or any vega_tpu
runtime module (it lints source trees it never executes).

Rule protocol
-------------
A rule is registered with :func:`rule` and comes in two shapes:

* per-file: ``check(FileCtx) -> findings`` — runs once per file; its
  findings are cached per file.
* project (``project=True``): a cheap per-file ``extract(FileCtx) ->
  data`` (cached per file, shareable between rules via ``extract_key``)
  plus a global ``check(records) -> findings`` combining every file's
  extraction — the two-pass shape the cross-file analyses (lock-order
  VG003, the VG009–VG011 contract index) need. ``records`` is a list of
  ``(display, data)`` pairs for files whose extraction returned data.

Result cache
------------
Parsing ~100 files and walking their ASTs dominates the sweep, so
:func:`run_lint` keeps a pickle cache keyed on each file's
``(mtime_ns, size)`` plus a fingerprint of the engine/rules sources:
an unchanged file contributes its cached per-file findings, pragmas and
project-rule extractions without being re-read or re-parsed — only the
cheap global combine runs every time. ``VEGA_TPU_LINT_CACHE`` overrides
the cache path ("0"/"off" disables); ``--no-cache`` disables per run.

Pragmas
-------
A finding on line N is suppressed when line N — or a standalone comment
line directly above it — carries::

    # vegalint: ignore[VG003] — one-line justification

The justification is MANDATORY: a pragma without one is itself a finding
(VG000, not suppressible), which is how the acceptance criterion "every
ignore carries a justification" is machine-enforced rather than reviewed.
``ignore[*]`` suppresses every rule on that line (same justification
duty). A pragma that no longer suppresses anything is reported WITH its
orphaned justification text, so stale pragmas cannot silently rot after
a refactor moves or fixes the code they annotated.
"""

from __future__ import annotations

import ast
import copy
import dataclasses
import hashlib
import io
import json
import os
import pickle
import re
import sys
import tempfile
import tokenize
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

PRAGMA_RE = re.compile(
    r"#\s*vegalint:\s*ignore\[([^\]]*)\]\s*(.*)$"
)
# Leading em-dash / dash / colon before the justification text.
_JUSTIFY_STRIP = " \t—–:-"

# Stable schema version of the JSON reporter output (finding dicts carry
# rule / path / line / col / message / suppressed / justification).
# Schema 2 (vegalint v3): same finding shape as schema 1 — the bump marks
# the addition of the `--explain-role` document ({schema, query, matches})
# sharing the version number; consumers of the sweep document need no
# changes beyond accepting schema == 2.
JSON_SCHEMA = 2


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # as given on the command line (relative where possible)
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if self.justification is None:
            d.pop("justification")
        # "pragma state" for CI artifact consumers: suppressed findings
        # carry their justification, live ones carry "none".
        d["pragma"] = "justified" if self.suppressed else "none"
        return d

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.justification if self.suppressed \
            else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}{tag}"


@dataclasses.dataclass
class Rule:
    id: str
    title: str
    doc: str  # rationale + example, surfaced by --list-rules and the docs
    check: Callable
    project: bool = False  # True: check(records); else check(FileCtx)
    extract: Optional[Callable] = None  # project rules: extract(FileCtx)
    extract_key: Optional[str] = None  # share one extraction across rules


_RULES: Dict[str, Rule] = {}


def rule(rule_id: str, title: str, doc: str = "", project: bool = False,
         extract: Optional[Callable] = None,
         extract_key: Optional[str] = None):
    def register(fn):
        _RULES[rule_id] = Rule(rule_id, title, doc or (fn.__doc__ or ""),
                               fn, project, extract, extract_key)
        return fn

    return register


def all_rules() -> Dict[str, Rule]:
    # Importing the rules module populates the registry on first use.
    from vega_tpu.lint import rules  # noqa: F401

    return dict(_RULES)


class FileCtx:
    """One parsed source file plus the import-alias map rules share."""

    def __init__(self, path: str, display: str, source: str):
        self.path = path
        self.display = display  # normalized, '/'-separated, for reporting
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = _collect_aliases(self.tree)
        # pragma line -> (set of rule ids or {'*'}, justification, col,
        # standalone). Pragmas are read from real COMMENT tokens, so a
        # docstring that *mentions* the syntax (this engine's own, say)
        # is not a pragma. `standalone` records whether the pragma is a
        # comment-only line (then it also covers the line below).
        self.pragmas: Dict[int, Tuple[set, str, int, bool]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if m:
                    ids = {s.strip() for s in m.group(1).split(",")
                           if s.strip()}
                    just = m.group(2).strip(_JUSTIFY_STRIP).strip()
                    line = tok.start[0]
                    text = self.lines[line - 1].lstrip() \
                        if 1 <= line <= len(self.lines) else ""
                    self.pragmas[line] = (
                        ids, just, tok.start[1] + m.start() + 1,
                        text.startswith("#"))
        except tokenize.TokenError:
            pass  # the ast parse already succeeded; just no pragmas

    # ---------------------------------------------------------- path scoping
    def in_dir(self, *parts: str) -> bool:
        """True when the file lives under a directory path containing the
        given '/'-joined fragment (e.g. in_dir('vega_tpu', 'tpu'))."""
        return "/" + "/".join(parts) + "/" in "/" + self.display

    def endswith(self, suffix: str) -> bool:
        return self.display.endswith(suffix)

    @property
    def module(self) -> str:
        """Dotted module name anchored at the last 'vega_tpu' path segment
        (lock keys and messages use it); top-level scripts use the stem."""
        parts = self.display.split("/")
        anchors = [i for i, p in enumerate(parts[:-1]) if p == "vega_tpu"]
        if anchors:
            parts = parts[anchors[-1]:]
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    # ----------------------------------------------------------- ast helpers
    def qualified(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases
        expanded: `jnp.nonzero` -> 'jax.numpy.nonzero' after
        `import jax.numpy as jnp`."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        return ".".join([base] + list(reversed(parts)))


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


# --------------------------------------------------------------- file records
@dataclasses.dataclass
class FileRecord:
    """Everything a single file contributes to a lint run — the cacheable
    unit. `findings` holds every per-file rule's output (select filters at
    assembly time, so one cache serves every --select subset); `extracts`
    holds the project rules' per-file extraction data."""

    display: str
    stat: Tuple[int, int]  # (mtime_ns, size)
    error: Optional[str] = None
    pragmas: Dict[int, Tuple[set, str, int, bool]] = \
        dataclasses.field(default_factory=dict)
    findings: List[Finding] = dataclasses.field(default_factory=list)
    extracts: Dict[str, Any] = dataclasses.field(default_factory=dict)


def _build_record(path: str, display: str, stat: Tuple[int, int],
                  rules: Dict[str, Rule]) -> FileRecord:
    rec = FileRecord(display, stat)
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        ctx = FileCtx(path, display, source)
    except (SyntaxError, UnicodeDecodeError, OSError) as exc:
        rec.error = f"{display}: {type(exc).__name__}: {exc}"
        return rec
    rec.pragmas = dict(ctx.pragmas)
    extractors: Dict[str, Callable] = {}
    for r in rules.values():
        if not r.project:
            rec.findings.extend(r.check(ctx))
        elif r.extract is not None:
            extractors.setdefault(r.extract_key or r.id, r.extract)
    for key, fn in extractors.items():
        data = fn(ctx)
        if data is not None:
            rec.extracts[key] = data
    return rec


# --------------------------------------------------------------- result cache
def _cache_path() -> Optional[str]:
    override = os.environ.get("VEGA_TPU_LINT_CACHE")
    if override is not None:
        if override.strip().lower() in ("", "0", "off", "none"):
            return None
        return override
    # Default location: a PRIVATE per-user directory (0700, ownership
    # verified) under the temp dir. pickle.load executes arbitrary code,
    # so a predictable world-writable path would let any local user plant
    # a payload for the next lint run — if the directory is foreign or
    # group/world-accessible, run uncached instead.
    uid = getattr(os, "getuid", lambda: 0)()
    base = os.path.join(tempfile.gettempdir(), f"vegalint-{uid}")
    try:
        os.makedirs(base, mode=0o700, exist_ok=True)
        st = os.stat(base)
        if st.st_uid != uid or (st.st_mode & 0o077):
            return None
    except OSError:
        return None
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    tag = hashlib.sha1(root.encode()).hexdigest()[:12]
    return os.path.join(base, f"cache-{tag}.pkl")


def _cache_fingerprint() -> str:
    """Any change to the engine or the rules invalidates every cached
    record — rule logic is part of the result."""
    parts = ["schema=2", f"py={sys.version_info[:2]}"]
    from vega_tpu.lint import callgraph as cg_mod
    from vega_tpu.lint import rules as rules_mod

    for mod_file in (os.path.abspath(__file__),
                     os.path.abspath(rules_mod.__file__),
                     os.path.abspath(cg_mod.__file__)):
        try:
            st = os.stat(mod_file)
            parts.append(f"{mod_file}:{st.st_mtime_ns}:{st.st_size}")
        except OSError:
            parts.append(f"{mod_file}:?")
    return "|".join(parts)


def _load_cache(cache_file: str, fingerprint: str) -> Dict:
    try:
        with open(cache_file, "rb") as f:
            blob = pickle.load(f)
        if blob.get("fp") == fingerprint:
            return blob["records"]
    except Exception:  # corrupt/foreign cache: start cold
        pass
    return {}


def _save_cache(cache_file: str, fingerprint: str, records: Dict) -> None:
    # Prune records for files that no longer exist so fixture churn from
    # test runs cannot grow the cache without bound.
    live = {k: v for k, v in records.items() if os.path.exists(k[0])}
    try:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(cache_file),
                                   prefix=".vegalint-")
        with os.fdopen(fd, "wb") as f:
            pickle.dump({"fp": fingerprint, "records": live}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, cache_file)
    except OSError:
        pass  # caching is best-effort; the sweep result is unaffected


# ------------------------------------------------------------- clean stamp
# `scripts/lint.sh --changed` lints only files modified since the last
# CLEAN full sweep. The stamp rides next to the result cache (same
# private-dir guarantees); no stamp (or cache disabled) means --changed
# degrades to the full sweep, never to a vacuous pass.
def clean_stamp_path() -> Optional[str]:
    cp = _cache_path()
    return cp + ".stamp" if cp else None


def write_clean_stamp() -> None:
    sp = clean_stamp_path()
    if sp is None:
        return
    try:
        with open(sp, "w") as f:
            f.write("clean full sweep marker (mtime is the stamp)\n")
    except OSError:
        pass


def read_clean_stamp() -> Optional[int]:
    """mtime_ns of the last clean full sweep, or None."""
    sp = clean_stamp_path()
    if sp is None:
        return None
    try:
        return os.stat(sp).st_mtime_ns
    except OSError:
        return None


def changed_since_stamp(paths: Iterable[str]) -> Optional[List[str]]:
    """Files under `paths` modified after the last clean full sweep, or
    None when no stamp exists (caller must fall back to a full sweep)."""
    stamp = read_clean_stamp()
    if stamp is None:
        return None
    out: List[str] = []
    for path in discover(paths):
        try:
            if os.stat(path).st_mtime_ns > stamp:
                out.append(path)
        except OSError:
            out.append(path)  # vanished/ephemeral: let the sweep report it
    return out


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # unsuppressed, reported, gate exit status
    suppressed: List[Finding]
    files: int
    errors: List[str]  # unparseable files etc.
    cache_hits: int = 0  # files served from the mtime-keyed result cache

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def to_dict(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "schema": JSON_SCHEMA,
            "ok": self.ok,
            "files": self.files,
            "cache_hits": self.cache_hits,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "errors": self.errors,
            "by_rule": counts,
        }


def discover(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _collect_records(paths: List[str], build_rules: Dict[str, Rule],
                     cache: bool, errors: List[str]
                     ) -> Tuple[List[FileRecord], int]:
    """The discovery + mtime-cache loop shared by run_lint and the
    --explain-role record gatherer."""
    cache_file = _cache_path() if cache else None
    fingerprint = _cache_fingerprint() if cache_file else ""
    store: Dict = _load_cache(cache_file, fingerprint) if cache_file else {}
    dirty = False
    cache_hits = 0
    records: List[FileRecord] = []
    for path in discover(paths):
        display = os.path.normpath(path).replace(os.sep, "/")
        try:
            st = os.stat(path)
        except OSError as exc:
            errors.append(f"{display}: OSError: {exc}")
            continue
        stat = (st.st_mtime_ns, st.st_size)
        key = (os.path.abspath(path), display)
        rec = store.get(key)
        if rec is not None and rec.stat == stat:
            cache_hits += 1
        else:
            rec = _build_record(path, display, stat, build_rules)
            store[key] = rec
            dirty = True
        records.append(rec)
    if cache_file and dirty:
        _save_cache(cache_file, fingerprint, store)
    return records, cache_hits


def gather_extracts(paths: Iterable[str], extract_key: str,
                    cache: bool = True) -> List[Tuple[str, Any]]:
    """The (display, data) pairs a project rule's global combine would
    see — the record source for `--explain-role` (and tests that poke the
    call graph directly)."""
    errors: List[str] = []
    records, _hits = _collect_records(list(paths), all_rules(), cache,
                                      errors)
    return [(rec.display, rec.extracts[extract_key]) for rec in records
            if not rec.error and extract_key in rec.extracts]


def run_lint(paths: Iterable[str],
             select: Optional[Iterable[str]] = None,
             cache: bool = True) -> LintResult:
    rules = all_rules()
    if select:
        keep = set(select)
        unknown = keep - set(rules)
        if unknown:
            # A typo'd --select silently checking nothing would report the
            # invariant gate green — fail loudly instead.
            raise ValueError(f"unknown rule id(s) in select: "
                             f"{sorted(unknown)}; known: {sorted(rules)}")
    errors: List[str] = []
    paths = list(paths)
    for p in paths:
        # Same rationale: a mistyped path must not make the gate pass
        # vacuously.
        if not os.path.exists(p):
            errors.append(f"{p}: path does not exist")
        elif not os.path.isdir(p) and not p.endswith(".py"):
            errors.append(f"{p}: not a directory or .py file")

    active = rules if not select else \
        {rid: r for rid, r in rules.items() if rid in set(select)}
    # Records built for the cache run EVERY rule (one cache serves every
    # --select subset); with no cache to fill, building unselected rules'
    # results would be pure waste — narrow to the active set.
    build_rules = rules if cache else active
    records, cache_hits = _collect_records(paths, build_rules, cache,
                                           errors)

    raw: List[Finding] = []
    for rec in records:
        if rec.error:
            errors.append(rec.error)
            continue
        # Copies: cached Finding objects must never be mutated by pragma
        # application (the cache would leak one run's suppression state
        # into the next).
        raw.extend(copy.copy(f) for f in rec.findings if f.rule in active)
    for r in active.values():
        if not r.project:
            continue
        key = r.extract_key or r.id
        data = [(rec.display, rec.extracts[key]) for rec in records
                if not rec.error and key in rec.extracts]
        raw.extend(r.check(data))

    by_display = {rec.display: rec for rec in records if not rec.error}
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    used_pragmas: Dict[Tuple[str, int], bool] = {}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        rec = by_display.get(f.path)
        hit = _pragma_for(rec, f) if rec is not None else None
        if hit is not None and f.rule != "VG000":
            line, (_ids, just, _col, _standalone) = hit
            used_pragmas[(f.path, line)] = True
            f.suppressed = True
            f.justification = just or None
            suppressed.append(f)
        else:
            findings.append(f)

    # Pragma hygiene (VG000): a pragma must carry a justification; a pragma
    # that names no known rule, or suppresses nothing, is dead weight —
    # either the invariant code was fixed (delete the pragma) or the rule
    # drifted (fix the rule). Not themselves suppressible.
    known = set(rules) | {"*"}
    for rec in records:
        if rec.error:
            continue
        for line, (ids, just, col, _standalone) in sorted(
                rec.pragmas.items()):
            if not just:
                findings.append(Finding(
                    "VG000", rec.display, line, col,
                    "pragma without justification — write "
                    "'# vegalint: ignore[RULE] — why this is safe'"))
            unknown = ids - known
            if unknown:
                findings.append(Finding(
                    "VG000", rec.display, line, col,
                    f"pragma names unknown rule(s) {sorted(unknown)}"))
            elif select is None \
                    and not used_pragmas.get((rec.display, line)):
                findings.append(Finding(
                    "VG000", rec.display, line, col,
                    f"pragma suppresses nothing (rules {sorted(ids)} did "
                    "not fire here) — delete it or re-anchor it; orphaned "
                    f"justification: {just!r}"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result = LintResult(findings, suppressed,
                        len([r for r in records if not r.error]), errors,
                        cache_hits=cache_hits)
    # A clean FULL sweep (every rule, cache on) arms `--changed`: only a
    # run that proved the whole tree clean may move the stamp.
    if select is None and cache and result.ok:
        write_clean_stamp()
    return result


def _pragma_for(rec: FileRecord, f: Finding):
    """Pragma applying to finding `f`: same line, or a standalone comment
    line directly above."""
    for line in (f.line, f.line - 1):
        hit = rec.pragmas.get(line)
        if hit is None:
            continue
        if line == f.line - 1 and not hit[3]:
            continue  # trailing pragma on the previous code line
        ids = hit[0]
        if f.rule in ids or "*" in ids:
            return line, hit
    return None


# ------------------------------------------------------------------ reporters
def render_text(result: LintResult) -> str:
    lines = [f.render() for f in result.findings]
    lines.extend(f"error: {e}" for e in result.errors)
    lines.append(
        f"vegalint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, {result.files} file(s), "
        f"{result.cache_hits} cached"
    )
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_dict(), indent=1, sort_keys=True)
