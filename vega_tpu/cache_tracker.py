"""Cached-partition location registry + get_or_compute.

Reference: src/cache_tracker.rs — driver-side rdd->partition->hosts registry
(:289-317) feeding scheduler cache locality, and the get_or_compute
partition materializer (:327-365) that the reference never actually calls
(SURVEY.md §2.6). vega_tpu calls it from RDD.iterator, completing the cache
feature.
"""

from __future__ import annotations

import threading
from typing import Dict, List

from vega_tpu.cache import KeySpace
from vega_tpu.env import Env
from vega_tpu.lint.sync_witness import named_lock


class CacheTracker:
    def __init__(self):
        # rdd_id -> partition -> [host uris]
        self._locs: Dict[int, Dict[int, List[str]]] = {}
        self._lock = named_lock("cache_tracker.CacheTracker._lock")

    def register_rdd(self, rdd_id: int, num_partitions: int) -> None:
        with self._lock:
            self._locs.setdefault(rdd_id, {})

    def unregister_rdd(self, rdd_id: int) -> None:
        with self._lock:
            self._locs.pop(rdd_id, None)

    def add_host(self, rdd_id: int, partition: int, host: str) -> None:
        with self._lock:
            self._locs.setdefault(rdd_id, {}).setdefault(partition, [])
            if host not in self._locs[rdd_id][partition]:
                self._locs[rdd_id][partition].insert(0, host)

    def drop_host(self, rdd_id: int, partition: int, host: str) -> None:
        with self._lock:
            locs = self._locs.get(rdd_id, {}).get(partition, [])
            if host in locs:
                locs.remove(host)

    def drop_executor(self, executor_id: str) -> int:
        """Executor loss: drop the lost executor from EVERY cached
        partition's location list in one sweep (the cache-side mirror of
        Stage.remove_outputs_on_server / unregister_server_outputs) so
        _get_preferred_locs never points a fresh stage at a dead
        executor's cache. Entries are executor ids (get_or_compute
        registers env.executor_id), so this never collateral-drops a
        co-hosted survivor. Returns the number of entries removed."""
        removed = 0
        with self._lock:
            for parts in self._locs.values():
                for p, hosts in parts.items():
                    if executor_id in hosts:
                        parts[p] = [h for h in hosts if h != executor_id]
                        removed += 1
        return removed

    def get_location_snapshot(self) -> Dict[int, Dict[int, List[str]]]:
        """Reference: cache_tracker.rs:302-317."""
        with self._lock:
            return {
                rdd: {p: list(hosts) for p, hosts in parts.items()}
                for rdd, parts in self._locs.items()
            }

    def get_cache_locs(self, rdd_id: int, partition: int) -> List[str]:
        with self._lock:
            return list(self._locs.get(rdd_id, {}).get(partition, []))


# Per-partition materialization locks so two tasks computing the same cached
# partition don't duplicate work (the reference busy-waits on a 'loading' set,
# cache_tracker.rs:337-340).
_loading_locks: Dict = {}
_loading_guard = named_lock("cache_tracker._loading_guard")


def get_or_compute(rdd, split, task_context=None):
    """Reference: cache_tracker.rs:327-365."""
    env = Env.get()
    key = (KeySpace.RDD, rdd.rdd_id, split.index)
    cached = env.cache.get(*key)
    if cached is not None:
        return iter(cached)
    with _loading_guard:
        lock = _loading_locks.setdefault(key, threading.Lock())
    with lock:
        cached = env.cache.get(*key)
        if cached is not None:
            return iter(cached)
        data = list(rdd.compute(split, task_context))
        env.cache.put(KeySpace.RDD, rdd.rdd_id, split.index, data,
                      level=getattr(rdd, "storage_level", None))
        tracker = env.cache_tracker
        if tracker is not None:
            host = env.executor_id or "local"
            tracker.add_host(rdd.rdd_id, split.index, host)
        return iter(data)
