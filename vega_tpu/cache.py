"""Bounded in-memory partition cache with real LRU eviction.

Reference: src/cache.rs — BoundedMemoryCache keyed ((key_space, rdd_id),
partition) with a hardcoded 2000MB cap and eviction left as todo!()
(cache.rs:68-76). vega_tpu implements the eviction the reference stubbed:
LRU by insertion/access order, evicting cold entries until under capacity.
"""

from __future__ import annotations

import enum
import sys
from collections import OrderedDict
from typing import Any, Callable, List, Optional, Tuple
from vega_tpu.lint.sync_witness import named_lock


class KeySpace(enum.Enum):
    """Reference: src/cache.rs:80-103."""

    RDD = 0
    BROADCAST = 1
    # Streaming receiver blocks (vega_tpu/streaming/source.py): keyed
    # (stream_id, block_seq); replayable micro-batch inputs, removed by
    # the streaming context once every window that references them has
    # committed. No reference-repo counterpart (streaming was never
    # ported there).
    STREAM = 2


Key = Tuple[KeySpace, int, int]  # (space, datum_id, partition)


def _elem_sizeof(elem: Any) -> int:
    """Size of one container element (no per-container overhead floor)."""
    import numpy as np

    if isinstance(elem, np.ndarray):
        return elem.nbytes
    if isinstance(elem, (list, tuple, dict)):
        return _sizeof(elem)
    return max(sys.getsizeof(elem), 16)


def _sizeof(value: Any) -> int:
    """Approximate byte size of a cached partition.

    Lists/tuples are sized from an evenly-spaced sample of min(len, 16)
    elements, not element 0 alone: partitions are routinely heterogeneous
    (ints mixed with arrays/strings) or ragged (element sizes varying by
    orders of magnitude), and a single-element extrapolation under- or
    over-accounts those wildly — bad accounting either thrashes the LRU or
    lets the cache blow past its capacity."""
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return value.nbytes
        if isinstance(value, (list, tuple)):
            n = len(value)
            if n == 0:
                return 64
            k = min(n, 16)
            sample = [value[(i * n) // k] for i in range(k)]
            if all(isinstance(s, np.ndarray) for s in sample):
                try:
                    return sum(a.nbytes for a in value)  # exact, cheap
                except AttributeError:
                    pass  # heterogeneous tail: fall through to sampling
            per = sum(_elem_sizeof(s) for s in sample) / k
            return 64 + int(n * per)
        if isinstance(value, dict):
            return 64 + sum(
                _sizeof(k) + _sizeof(v) for k, v in list(value.items())[:100]
            ) * max(1, len(value) // max(1, min(len(value), 100)))
    except Exception:
        pass
    return max(sys.getsizeof(value), 64)


class BoundedMemoryCache:
    def __init__(self, capacity_bytes: int):
        self._capacity = capacity_bytes
        self._entries: "OrderedDict[Key, Tuple[Any, int]]" = OrderedDict()
        self._used = 0
        self._lock = named_lock("cache.BoundedMemoryCache._lock")
        self.evictions = 0
        # Eviction hook (key, value, size), set by TieredCache (store/) to
        # demote evicted entries to disk instead of losing them. Called
        # OUTSIDE the lock, and — crucially — while the victim is STILL
        # readable from memory: the entry only leaves after the hook
        # returns, so a concurrent get() always finds the partition in one
        # tier (a pop-then-demote window would read as a double miss and
        # recompute a partition that was never lost — the same spurious-
        # miss race ShuffleStore._spill_oldest documents).
        self.on_evict: Optional[Callable[[Key, Any, int], None]] = None

    def put(self, space: KeySpace, datum_id: int, partition: int, value: Any) -> bool:
        """Insert; returns False if the single value exceeds capacity
        (reference: cache.rs:50-66)."""
        size = _sizeof(value)
        if size > self._capacity:
            return False
        key = (space, datum_id, partition)
        with self._lock:
            if key in self._entries:
                _, old = self._entries.pop(key)
                self._used -= old
            self._entries[key] = (value, size)
            self._used += size
            victims = self._peek_victims(exclude=key)
        self._evict(victims)
        return True

    def set_capacity(self, capacity_bytes: int) -> None:
        """Retarget the capacity (benchmark/test knob); shrinking evicts
        (LRU-first, demotion hook honored) until under the new cap."""
        with self._lock:
            self._capacity = capacity_bytes
            victims = self._peek_victims()
        self._evict(victims)

    def _peek_victims(self, exclude: Optional[Key] = None
                      ) -> List[Tuple[Key, Any, int]]:
        """LRU-first victims bringing used bytes under capacity. Caller
        holds the lock. Victims are only PEEKED — they stay readable until
        _evict demotes then removes them."""
        over = self._used - self._capacity
        victims: List[Tuple[Key, Any, int]] = []
        if over <= 0:
            return victims
        for ekey, (evalue, esize) in self._entries.items():
            if ekey == exclude:
                continue
            victims.append((ekey, evalue, esize))
            over -= esize
            if over <= 0:
                break
        return victims

    def _evict(self, victims: List[Tuple[Key, Any, int]]) -> None:
        """Demote (hook) THEN remove, per victim. The removal is identity-
        guarded: if a fresh put replaced the entry while the hook ran, the
        new value wins and stays (concurrent evictions of the same victim
        are likewise idempotent — only the actual remover accounts it)."""
        hook = self.on_evict
        for ekey, evalue, esize in victims:
            if hook is not None:
                try:
                    hook(ekey, evalue, esize)
                except Exception:  # noqa: BLE001 — demotion failure ≡ plain drop
                    import logging

                    logging.getLogger("vega_tpu").exception(
                        "cache eviction hook failed; entry dropped")
            with self._lock:
                entry = self._entries.get(ekey)
                if entry is not None and entry[0] is evalue:
                    del self._entries[ekey]
                    self._used -= entry[1]
                    self.evictions += 1

    def get(self, space: KeySpace, datum_id: int, partition: int) -> Optional[Any]:
        key = (space, datum_id, partition)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)  # LRU touch
            return entry[0]

    def contains(self, space: KeySpace, datum_id: int, partition: int) -> bool:
        with self._lock:
            return (space, datum_id, partition) in self._entries

    def remove(self, space: KeySpace, datum_id: int, partition: int) -> None:
        """Drop one entry (no eviction hook — an explicit removal is not a
        demotion)."""
        with self._lock:
            entry = self._entries.pop((space, datum_id, partition), None)
            if entry is not None:
                self._used -= entry[1]

    def remove_datum(self, space: KeySpace, datum_id: int) -> None:
        """Drop every partition of one RDD/broadcast (unpersist)."""
        with self._lock:
            doomed = [k for k in self._entries if k[0] is space and k[1] == datum_id]
            for k in doomed:
                _, size = self._entries.pop(k)
                self._used -= size

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._used = 0
