from vega_tpu.utils.bounded_priority_queue import BoundedPriorityQueue

__all__ = ["BoundedPriorityQueue"]
