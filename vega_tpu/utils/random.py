"""Pluggable partition samplers (reference: src/utils/random.rs).

BernoulliSampler / PoissonSampler / BernoulliCellSampler mirror
random.rs:58-297 including the gap-sampling optimization for small fractions
(random.rs:123-150: skip ahead geometric(p) elements instead of flipping a
coin per element). Sample-size -> fraction bounds mirror random.rs:318-358.
"""

from __future__ import annotations

import math
from typing import Iterator, TypeVar

import numpy as np

T = TypeVar("T")

# Below this fraction, gap sampling beats per-element draws
# (reference: random.rs:36-40).
GAP_SAMPLING_FRACTION_THRESHOLD = 0.4


class RandomSampler:
    """Reference: random.rs trait RandomSampler (:58-70)."""

    def __init__(self, fraction: float, seed: int | None = None):
        self.fraction = fraction
        self.seed = seed

    def sample(self, items: Iterator[T], split_seed: int) -> Iterator[T]:
        raise NotImplementedError

    def _rng(self, split_seed: int) -> np.random.Generator:
        base = self.seed if self.seed is not None else 0xC0FFEE
        return np.random.Generator(np.random.PCG64([base, split_seed]))


class BernoulliSampler(RandomSampler):
    """Sampling without replacement (reference: random.rs:153-219)."""

    def sample(self, items, split_seed):
        p = self.fraction
        if p <= 0.0:
            return
        rng = self._rng(split_seed)
        if p >= 1.0:
            yield from items
            return
        if p <= GAP_SAMPLING_FRACTION_THRESHOLD:
            # Gap sampling (reference: random.rs:123-150).
            log1mp = math.log1p(-p)
            skip = int(math.log(rng.random() or 1e-300) / log1mp)
            for item in items:
                if skip > 0:
                    skip -= 1
                    continue
                yield item
                skip = int(math.log(rng.random() or 1e-300) / log1mp)
        else:
            for item in items:
                if rng.random() < p:
                    yield item


class PoissonSampler(RandomSampler):
    """Sampling with replacement (reference: random.rs:222-297)."""

    def sample(self, items, split_seed):
        lam = self.fraction
        if lam <= 0.0:
            return
        rng = self._rng(split_seed)
        for item in items:
            count = rng.poisson(lam)
            for _ in range(count):
                yield item


class BernoulliCellSampler(RandomSampler):
    """Accept items whose draw falls in [lb, ub); basis of random_split
    (reference: random.rs:80-120)."""

    def __init__(self, lb: float, ub: float, complement: bool = False,
                 seed: int | None = None):
        super().__init__(ub - lb, seed)
        self.lb = lb
        self.ub = ub
        self.complement = complement

    def sample(self, items, split_seed):
        rng = self._rng(split_seed)
        for item in items:
            x = rng.random()
            inside = self.lb <= x < self.ub
            if inside != self.complement:
                yield item


def compute_fraction_for_sample_size(size: int, total: int,
                                     with_replacement: bool) -> float:
    """Oversampling fraction so P(sample >= size) is high
    (reference: random.rs:318-358)."""
    if with_replacement and size < 12:
        # Small Poisson means need a larger multiplier (random.rs:322-330).
        return float(size) / total * 4.0
    frac = float(size) / total
    delta = 1e-4
    gamma = -math.log(delta) / total
    return min(1.0, max(1e-10, frac + gamma + math.sqrt(gamma * gamma + 2 * gamma * frac)))
