"""Top-K bounded heap for top / take_ordered
(reference: src/utils/bounded_priority_queue.rs:8-58).
"""

from __future__ import annotations

import heapq
from typing import Callable, Iterable, List, Optional, TypeVar

T = TypeVar("T")


class BoundedPriorityQueue:
    """Keeps the K smallest items by `key` (min-K). For top-K largest, pass a
    negating key. Merge two queues with `merge` (used when combining
    per-partition results on the driver, reference: rdd.rs:1106-1153)."""

    def __init__(self, capacity: int, key: Optional[Callable] = None):
        self.capacity = capacity
        self.key = key or (lambda x: x)
        # Max-heap of (neg-rank...) — store (key, seq, item) with inverted
        # comparison via heapq on negated ordering trick: keep a max-heap by
        # pushing wrapped keys.
        self._heap: List = []  # entries: (_NegKey(key), seq, item)
        self._seq = 0

    def push(self, item: T) -> None:
        k = self.key(item)
        entry = (_NegKey(k), self._seq, item)
        self._seq += 1
        if len(self._heap) < self.capacity:
            heapq.heappush(self._heap, entry)
        else:
            # Heap root is the *largest* key (worst of the kept smallest-K).
            if k < self._heap[0][0].value:
                heapq.heapreplace(self._heap, entry)

    def extend(self, items: Iterable[T]) -> "BoundedPriorityQueue":
        for item in items:
            self.push(item)
        return self

    def merge(self, other: "BoundedPriorityQueue") -> "BoundedPriorityQueue":
        for _, _, item in other._heap:
            self.push(item)
        return self

    def items_sorted(self) -> List[T]:
        return [item for _, _, item in
                sorted(self._heap, key=lambda e: e[0].value)]

    def __len__(self):
        return len(self._heap)


class _NegKey:
    """Inverts comparison so heapq's min-heap behaves as a max-heap."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def __lt__(self, other):
        return other.value < self.value

    def __eq__(self, other):
        return other.value == self.value
