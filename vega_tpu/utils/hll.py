"""HyperLogLog distinct-count sketch (Spark-parity count_approx_distinct;
the reference has no distinct-count estimator).

Standard HLL with the empirical bias corrections: m = 2^p registers, hash
via the framework's splitmix64 (partitioner.hash_key, so any hashable item
sketches consistently with shuffle hashing), linear counting for the small
range and the large-range correction for the top end.
"""

from __future__ import annotations

import math

import numpy as np

from vega_tpu.partitioner import hash_key


class HyperLogLog:
    def __init__(self, precision: int = 14):
        if not 4 <= precision <= 18:
            raise ValueError("precision must be in [4, 18]")
        self.p = precision
        self.m = 1 << precision
        self.registers = np.zeros(self.m, dtype=np.uint8)

    @staticmethod
    def precision_for(relative_sd: float) -> int:
        """Smallest precision whose standard error (1.04/sqrt(m)) meets
        relative_sd."""
        p = math.ceil(2 * math.log2(1.04 / relative_sd))
        return max(4, min(18, p))

    def add(self, item) -> None:
        h = hash_key(item)
        idx = h >> (64 - self.p)
        rest = (h << self.p) & 0xFFFFFFFFFFFFFFFF
        # rank = leading zeros of the remaining 64-p bits, + 1
        if rest == 0:
            rank = (64 - self.p) + 1
        else:
            rank = 64 - rest.bit_length() + 1
        if rank > self.registers[idx]:
            self.registers[idx] = rank

    def merge_registers(self, other: np.ndarray) -> None:
        np.maximum(self.registers, other, out=self.registers)

    def estimate(self) -> int:
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        inv_sum = float(np.sum(np.exp2(-self.registers.astype(np.float64))))
        raw = alpha * m * m / inv_sum
        if raw <= 2.5 * m:
            zeros = int(np.count_nonzero(self.registers == 0))
            if zeros:
                return int(round(m * math.log(m / zeros)))  # linear counting
            return int(round(raw))
        two64 = 2.0 ** 64
        if raw > two64 / 30.0:
            return int(round(-two64 * math.log1p(-raw / two64)))
        return int(round(raw))
