"""Loader for the native C++ shuffle runtime (native/vega_native.cpp).

Builds on demand with the in-tree Makefile if the shared object is missing
(g++ is part of the toolchain); every caller has a pure-Python fallback, so
absence of a compiler degrades performance, not correctness.

Named ops shared with the device tier's segment fast paths.
"""

from __future__ import annotations

import logging
import os
import subprocess
from vega_tpu.lint.sync_witness import named_lock

log = logging.getLogger("vega_tpu")

OP_ADD, OP_MIN, OP_MAX, OP_PROD = 0, 1, 2, 3
OP_BY_NAME = {"add": OP_ADD, "min": OP_MIN, "max": OP_MAX, "prod": OP_PROD}

_PY_OPS = {
    "add": lambda a, b: a + b,
    "min": min,
    "max": max,
    "prod": lambda a, b: a * b,
}


def decode_pairs_py(blob: bytes, is_int: bool):
    """Pure-Python decoder for the native 16-byte row frames (i64 key +
    i64/f64 payload) — keeps heterogeneous clusters correct when one side
    lacks the compiled module."""
    import struct

    fmt = "<qq" if is_int else "<qd"
    return [(k, v) for k, v in struct.iter_unpack(fmt, blob)]


def decode(blob: bytes, is_int: bool):
    """Decode a native row frame with the compiled module when present,
    else the pure-Python fallback (single source of the selection logic)."""
    nat = get()
    if nat is not None:
        return nat.decode_pairs(blob, is_int)
    return decode_pairs_py(blob, is_int)


def merge_encoded_py(flagged_blobs, op_name: str):
    """Pure-Python equivalent of _vega_native.merge_encoded."""
    op = _PY_OPS[op_name]
    combined: dict = {}
    for blob, is_int in flagged_blobs:
        for k, v in decode_pairs_py(blob, bool(is_int)):
            combined[k] = op(combined[k], v) if k in combined else v
    return list(combined.items())


class StreamingMerge:
    """Incremental reduce-side merge: feed encoded buckets AS THEY ARRIVE
    off the pipelined fetch (shuffle/fetcher.fetch_stream), so the merge
    overlaps network time instead of following the last byte.

    Backed by the C++ accumulator (merge_state_new/feed/finish) when the
    compiled module is present, else an exact pure-Python dict (bignum
    ints — no overflow case). finish() returns the merged pair list, or
    None iff the NATIVE path saw an int64 overflow: the caller must then
    redo the merge on the exact Python path (results must be bit-identical
    whichever host path ran — silently rounding through doubles is the one
    thing this contract forbids). Not thread-safe: one reduce task, one
    merger."""

    def __init__(self, op_name: str):
        self._op = OP_BY_NAME[op_name]
        nat = get()
        if nat is not None and hasattr(nat, "merge_state_new"):
            self._nat = nat
            self._state = nat.merge_state_new()
            self._py_op = None
            self._acc = None
        else:
            self._nat = None
            self._state = None
            self._py_op = _PY_OPS[op_name]
            self._acc = {}

    def feed(self, payload: bytes, is_int: bool) -> None:
        if self._nat is not None:
            self._nat.merge_state_feed(self._state, payload,
                                       1 if is_int else 0, self._op)
            return
        op = self._py_op
        acc = self._acc
        for k, v in decode_pairs_py(payload, bool(is_int)):
            acc[k] = op(acc[k], v) if k in acc else v

    def finish(self):
        if self._nat is not None:
            return self._nat.merge_state_finish(self._state)
        return list(self._acc.items())

_lock = named_lock("native._lock")
_native = None
_load_attempted = False


def _try_build() -> bool:
    makefile_dir = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                                "native")
    if not os.path.isfile(os.path.join(makefile_dir, "Makefile")):
        return False
    try:
        subprocess.run(
            ["make", "-C", makefile_dir],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (subprocess.SubprocessError, OSError) as e:
        log.info("native build failed (pure-Python fallback in use): %s", e)
        return False


def get():
    """Return the _vega_native module, or None if unavailable."""
    global _native, _load_attempted
    if _native is not None or _load_attempted:
        return _native
    with _lock:
        if _native is not None or _load_attempted:
            return _native
        # _load_attempted flips only AFTER the attempt concludes: setting
        # it up front let the lock-free fast path above observe
        # attempted=True with _native still None WHILE the import ran on
        # another thread — so the first tasks of a concurrent stage
        # nondeterministically fell back to the pickled path (a silent
        # perf loss the push plan's pre-merge accounting surfaced).
        # Callers racing the import now block on _lock and get the module.
        try:
            try:
                from vega_tpu import _vega_native  # type: ignore[attr-defined]

                _native = _vega_native
            except ImportError:
                if _try_build():
                    try:
                        from vega_tpu import _vega_native  # type: ignore
                        _native = _vega_native
                    except ImportError:
                        _native = None
        finally:
            # finally: a CORRUPT .so whose module init raises something
            # other than ImportError must still conclude the attempt —
            # later callers degrade to the pure-Python fallback instead of
            # re-raising on every hot-path call.
            _load_attempted = True
        if _native is not None:
            log.info("native shuffle runtime loaded")
    return _native
